"""skylint: per-rule true positives/negatives, suppression layers,
JSON output schema, and the tier-1 guard that keeps the whole tree
clean (PR: skylint static-analysis pass).

Fixture files are written under tmp_path with repo-shaped relative
paths (models/, infer/engine.py, ...) because several rules scope by
path; everything runs in-process via skylint.lint_files so the guard
costs one AST walk, not a subprocess.
"""
import json
import os
import subprocess
import textwrap
from pathlib import Path

from skypilot_tpu import observability
from skypilot_tpu.devtools import analysis
from skypilot_tpu.devtools import skylint

REPO = Path(__file__).resolve().parents[2]


def _lint(tmp_path, relpath, source, rule=None, baseline=None):
    path = tmp_path / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    rules = skylint.all_rules()
    if rule is not None:
        rules = [r for r in rules if r.id == rule]
        assert rules, f'unknown rule {rule}'
    return skylint.lint_files([str(path)], rules=rules,
                              baseline=baseline,
                              baseline_root=str(tmp_path))


def _live(findings):
    return skylint.unsuppressed(findings)


# ---------------------------------------------------------------------
# host-sync
# ---------------------------------------------------------------------

_JITTED_ITEM = """
    import jax

    def _step(x):
        y = x.sum()
        return float(y.item())

    step = jax.jit(_step)
"""


def test_host_sync_flags_item_inside_jit(tmp_path):
    findings = _live(_lint(tmp_path, 'models/m.py', _JITTED_ITEM,
                           rule='host-sync'))
    symbols = {f.symbol for f in findings}
    assert '.item()' in symbols
    assert 'float()' in symbols       # float(<call>) syncs too


def test_host_sync_same_call_outside_jit_is_clean(tmp_path):
    src = """
        def _step(x):
            y = x.sum()
            return float(y.item())
    """
    assert not _live(_lint(tmp_path, 'models/m.py', src,
                           rule='host-sync'))


def test_host_sync_scan_body_and_decorator_and_scope(tmp_path):
    src = """
        import jax

        def body(carry, x):
            print('debug', carry)
            return carry, x

        out = jax.lax.scan(body, 0, xs)

        @jax.jit
        def fwd(x):
            import time
            t = time.time()
            return x * t
    """
    findings = _live(_lint(tmp_path, 'ops/k.py', src, rule='host-sync'))
    assert {f.symbol for f in findings} == {'print', 'time.time()'}
    # The speculative-decoding module hosts jitted kernels (acceptance,
    # draft scan): host-sync discipline applies there too.
    assert _live(_lint(tmp_path, 'infer/speculative.py', src,
                       rule='host-sync'))
    # Same file outside the compute layers: rule does not apply.
    assert not _live(_lint(tmp_path, 'serve/k.py', src,
                           rule='host-sync'))


# ---------------------------------------------------------------------
# retrace-hazard
# ---------------------------------------------------------------------

_DYNAMIC_TOPK = """
    import jax
    import jax.numpy as jnp

    def _decode(logits, top_k):
        if top_k > 0:
            logits = jnp.zeros((top_k,))
        return logits

    decode = jax.jit(_decode{statics})
"""


def test_retrace_flags_dynamic_scalar_param(tmp_path):
    findings = _live(_lint(tmp_path, 'm.py',
                           _DYNAMIC_TOPK.format(statics=''),
                           rule='retrace-hazard'))
    assert len(findings) == 1
    assert findings[0].symbol == '_decode.top_k'


def test_retrace_static_argnames_is_clean(tmp_path):
    src = _DYNAMIC_TOPK.format(
        statics=", static_argnames=('top_k',)")
    assert not _live(_lint(tmp_path, 'm.py', src,
                           rule='retrace-hazard'))


def test_retrace_partial_bound_params_are_static(tmp_path):
    src = """
        import functools
        import jax

        def train_step(state, batch, config):
            if config:
                return state
            return batch

        step = jax.jit(functools.partial(train_step, config=cfg))
    """
    assert not _live(_lint(tmp_path, 'm.py', src,
                           rule='retrace-hazard'))
    # ...but an unbound param in branch position still flags.
    src_bad = src.replace('config=cfg', 'state=s')
    bad = _live(_lint(tmp_path, 'm2.py', src_bad,
                      rule='retrace-hazard'))
    assert [f.symbol for f in bad] == ['train_step.config']


# ---------------------------------------------------------------------
# lock-discipline / thread-discipline
# ---------------------------------------------------------------------

_ENGINE_CLASS = """
    import threading

    class Engine:
        def __init__(self):
            self._lock = threading.Lock()
            self._queue = []

        def submit(self, item):
            with self._lock:
                self._queue.append(item)

        def drain(self):
            {drain_body}
"""


def test_lock_unlocked_write_fails(tmp_path):
    src = _ENGINE_CLASS.format(drain_body='self._queue = []')
    findings = _live(_lint(tmp_path, 'infer/engine.py', src,
                           rule='lock-discipline'))
    assert [f.symbol for f in findings] == ['Engine._queue']


def test_lock_locked_write_class_passes(tmp_path):
    src = _ENGINE_CLASS.format(
        drain_body='with self._lock:\n                self._queue = []')
    assert not _live(_lint(tmp_path, 'infer/engine.py', src,
                           rule='lock-discipline'))


def test_lock_init_writes_exempt_and_scope(tmp_path):
    src = _ENGINE_CLASS.format(drain_body='self._queue = []')
    # __init__'s unlocked self._queue = [] must not flag on the
    # passing variant (object not yet shared):
    ok = _ENGINE_CLASS.format(
        drain_body='with self._lock:\n                self._queue = []')
    assert not _live(_lint(tmp_path, 'infer/paging.py', ok,
                           rule='lock-discipline'))
    # Outside engine/paging/server the rule does not apply at all.
    assert not _live(_lint(tmp_path, 'serve/controller.py', src,
                           rule='lock-discipline'))


def test_thread_without_daemon_flags(tmp_path):
    src = """
        import threading
        t = threading.Thread(target=f)
        ok = threading.Thread(target=f, daemon=True)
        ok2 = threading.Thread(target=f, daemon=False)
    """
    findings = _live(_lint(tmp_path, 'x.py', src,
                           rule='thread-discipline'))
    assert len(findings) == 1
    assert findings[0].line == 3      # the daemon-less construction


# ---------------------------------------------------------------------
# stdout-purity
# ---------------------------------------------------------------------

def test_stdout_bare_print_flags(tmp_path):
    src = """
        import sys
        print('hello')
        sys.stdout.write('raw')
    """
    findings = _live(_lint(tmp_path, 'worker.py', src,
                           rule='stdout-purity'))
    assert {f.symbol for f in findings} == {'print',
                                            'sys.stdout.write'}


def test_stdout_stderr_json_and_cli_are_clean(tmp_path):
    src = """
        import json
        import sys
        print('note', file=sys.stderr)
        print(json.dumps({'metric': 1.0}))
    """
    assert not _live(_lint(tmp_path, 'worker.py', src,
                           rule='stdout-purity'))
    # cli.py owns stdout:
    assert not _live(_lint(tmp_path, 'cli.py', "print('usage: ...')",
                           rule='stdout-purity'))


# ---------------------------------------------------------------------
# metric-contract
# ---------------------------------------------------------------------

def test_metric_contract_tp_and_tn(tmp_path):
    src = """
        def make(reg):
            a = reg.counter('skytpu_requests_submitted_total', 'd')
            b = reg.counter('skytpu_bogus_series_total', 'd')
            c = reg.gauge('BadName', 'd')
            return a, b, c
    """
    findings = _live(_lint(tmp_path, 'm.py', src,
                           rule='metric-contract'))
    assert [f.symbol for f in findings] == ['skytpu_bogus_series_total',
                                            'BadName']
    assert 'skytpu_requests_submitted_total' \
        in observability.METRIC_CONTRACT


# ---------------------------------------------------------------------
# trace-discipline
# ---------------------------------------------------------------------

def test_trace_discipline_tp_and_tn(tmp_path):
    src = """
        def go(self, rid, name):
            self.events.record('replica_spawn', slot=1)      # TN
            self.events.record('bogus_event')                # TP: unknown
            self.events.record(name)                         # TP: dynamic
            self.traces.event(rid, 'first_token')            # TN
            self.traces.event(rid, 'not_a_thing')            # TP: unknown
            self.timeline.record('whatever')   # TN: other receiver
            timeline.event('scope-name')       # TN: other receiver
    """
    findings = _live(_lint(tmp_path, 'serve/x.py', src,
                           rule='trace-discipline'))
    assert {f.symbol for f in findings} == {'bogus_event', '.record',
                                            'not_a_thing'}
    # The implementations manipulate names generically: out of scope.
    assert not _live(_lint(tmp_path, 'observability/events.py', src,
                           rule='trace-discipline'))
    assert not _live(_lint(tmp_path, 'observability/tracing.py', src,
                           rule='trace-discipline'))


# ---------------------------------------------------------------------
# dtype-promotion
# ---------------------------------------------------------------------

def test_dtype_promotion_tp_and_tn(tmp_path):
    src = """
        import jax.numpy as jnp

        def f(x):
            bad = x * jnp.array(2.0)
            ok = x * jnp.array(2.0, dtype=x.dtype)
            also_ok = x * 2.0
            return bad, ok, also_ok
    """
    findings = _live(_lint(tmp_path, 'models/m.py', src,
                           rule='dtype-promotion'))
    assert [f.symbol for f in findings] == ['jnp.array']
    # Outside models/ the rule does not apply.
    assert not _live(_lint(tmp_path, 'ops/m.py', src,
                           rule='dtype-promotion'))


# ---------------------------------------------------------------------
# suppression layers
# ---------------------------------------------------------------------

def test_inline_disable_comment_suppresses(tmp_path):
    src = """
        print('tool output')  # skylint: disable=stdout-purity
        # skylint: disable=stdout-purity
        print('next line form')
        print('not suppressed')
    """
    findings = _lint(tmp_path, 'tool.py', src, rule='stdout-purity')
    assert len(findings) == 3
    assert [f.suppressed for f in findings] == [True, True, False]
    assert {f.suppressed_by for f in findings if f.suppressed} \
        == {'inline'}


def test_baseline_suppresses_by_rule_path_symbol(tmp_path):
    baseline = [skylint.BaselineEntry('stdout-purity', 'legacy/*.py',
                                      '*')]
    findings = _lint(tmp_path, 'legacy/old.py', "print('x')",
                     rule='stdout-purity', baseline=baseline)
    assert len(findings) == 1
    assert findings[0].suppressed
    assert findings[0].suppressed_by == 'baseline'
    # Same finding outside the globbed path stays live.
    findings = _lint(tmp_path, 'fresh/new.py', "print('x')",
                     rule='stdout-purity', baseline=baseline)
    assert not findings[0].suppressed


# ---------------------------------------------------------------------
# CLI: JSON schema + exit codes
# ---------------------------------------------------------------------

def test_cli_json_schema_and_exit_codes(tmp_path, capsys):
    bad = tmp_path / 'bad.py'
    bad.write_text("print('boom')\n")
    rc = skylint.main(['--format', 'json', '--no-baseline', str(bad)])
    out = capsys.readouterr().out
    assert rc == 1
    doc = json.loads(out)
    assert doc['version'] == 1
    assert set(doc['counts']) == {'total', 'unsuppressed'}
    assert doc['counts']['unsuppressed'] == 1
    (finding,) = doc['findings']
    assert set(finding) >= {'rule', 'path', 'line', 'col', 'symbol',
                            'message', 'suppressed', 'suppressed_by'}
    assert finding['rule'] == 'stdout-purity'
    assert finding['line'] == 1

    clean = tmp_path / 'clean.py'
    clean.write_text('x = 1\n')
    assert skylint.main(['--no-baseline', str(clean)]) == 0


def test_cli_unknown_rule_is_usage_error(tmp_path):
    p = tmp_path / 'x.py'
    p.write_text('x = 1\n')
    assert skylint.main(['--rule', 'nope', str(p)]) == 2


# ---------------------------------------------------------------------
# tier-1 guard: the shipped tree stays clean
# ---------------------------------------------------------------------

_LONG_NAP_LOOP = """
    import time

    def poll():
        while True:
            time.sleep(600)
"""

_SLEEP_NEGATIVES = """
    import time

    def ok(delay):
        time.sleep(600)              # not in a loop
        for _ in range(3):
            time.sleep(0.05)         # short poll
            time.sleep(delay)        # computed: caller budget-bends it

            def later():
                time.sleep(600)      # own schedule, not per-iteration
"""


def test_sleep_discipline_flags_long_constant_nap_in_loop(tmp_path):
    findings = _live(_lint(tmp_path, 'anywhere/poller.py',
                           _LONG_NAP_LOOP, rule='sleep-discipline'))
    assert len(findings) == 1
    assert findings[0].symbol == 'time.sleep'
    assert 'retry_with_backoff' in findings[0].message


def test_sleep_discipline_negatives_and_retry_py_scope(tmp_path):
    assert not _live(_lint(tmp_path, 'infer/server.py',
                           _SLEEP_NEGATIVES,
                           rule='sleep-discipline'))
    # utils/retry.py is the sanctioned home for long retry naps.
    assert not _live(_lint(tmp_path, 'skypilot_tpu/utils/retry.py',
                           _LONG_NAP_LOOP, rule='sleep-discipline'))


# ---------------------------------------------------------------------
# net-timeout
# ---------------------------------------------------------------------

_NET_NO_TIMEOUT = """
    import http.client
    import urllib.request

    def probe(url, host):
        r = urllib.request.urlopen(url)
        c = http.client.HTTPConnection(host)
        return r, c
"""

_NET_WITH_TIMEOUT = """
    import http.client
    import urllib.request

    def probe(url, host, **kw):
        a = urllib.request.urlopen(url, timeout=3.0)
        b = urllib.request.urlopen(url, None, 3.0)   # positional
        c = http.client.HTTPConnection(host, timeout=2)
        d = urllib.request.urlopen(url, **kw)        # forwarded surface
        return a, b, c, d
"""


def test_net_timeout_flags_unbounded_calls_in_serving_path(tmp_path):
    findings = _live(_lint(
        tmp_path, 'skypilot_tpu/serve/probe.py', _NET_NO_TIMEOUT,
        rule='net-timeout'))
    symbols = sorted(f.symbol for f in findings)
    assert symbols == ['http.client.HTTPConnection', 'urlopen']


def test_net_timeout_bounded_calls_and_scope_are_clean(tmp_path):
    assert not _live(_lint(
        tmp_path, 'skypilot_tpu/infer/client.py', _NET_WITH_TIMEOUT,
        rule='net-timeout'))
    # Outside serve/, infer/, benchmark/ the rule does not apply — an
    # offline devtool blocking on a download is annoying, not an
    # outage.
    assert not _live(_lint(
        tmp_path, 'skypilot_tpu/devtools/fetch.py', _NET_NO_TIMEOUT,
        rule='net-timeout'))


def test_tree_has_zero_unsuppressed_findings():
    """Gates every future PR: skylint over the package + bench.py via
    the committed .skylint-baseline must come back clean."""
    findings = skylint.lint_paths([str(REPO / 'skypilot_tpu'),
                                   str(REPO / 'bench.py')])
    live = _live(findings)
    assert not live, 'skylint findings:\n' + '\n'.join(
        f.render() for f in live)


# ---------------------------------------------------------------------
# pipeline-discipline
# ---------------------------------------------------------------------

_PIPELINE_DISPATCH_SYNC = """
    import jax
    import numpy as np

    class Engine:
        def _dispatch_plain(self, occupied):
            tok_dev = self._decode(occupied)
            toks = np.asarray(jax.device_get(tok_dev))  # BAD: sync
            return toks

        def _consume_step(self, handle):
            return handle
"""


def test_pipeline_discipline_flags_dispatch_side_sync(tmp_path):
    findings = _live(_lint(tmp_path, 'infer/engine.py',
                           _PIPELINE_DISPATCH_SYNC,
                           rule='pipeline-discipline'))
    assert findings, 'device_get on a _dev future in a dispatch-side ' \
                     'method must be flagged'
    assert any('jax.device_get' == f.symbol for f in findings)


def test_pipeline_discipline_flags_item_and_block_until_ready(tmp_path):
    src = """
        class Engine:
            def _dispatch_spec(self, occupied):
                out_dev, counts_dev = self._verify(occupied)
                out_dev.block_until_ready()        # BAD
                n = int(counts_dev.item())         # BAD (x2)
                return n

            def _consume_step(self, handle):
                return handle
    """
    findings = _live(_lint(tmp_path, 'infer/engine.py', src,
                           rule='pipeline-discipline'))
    symbols = {f.symbol for f in findings}
    assert '.block_until_ready()' in symbols
    assert '.item()' in symbols


def test_pipeline_discipline_consume_side_is_clean(tmp_path):
    src = """
        import jax
        import numpy as np

        class Engine:
            def _dispatch_plain(self, occupied):
                tok_dev = self._decode(occupied)
                return (tok_dev,)                  # futures only: OK

            def _fetch_handle(self, handle):
                handle.host = tuple(np.asarray(jax.device_get(a))
                                    for a in handle.arrays)

            def _consume_step(self, handle):
                toks = handle.host[0]
                return int(toks[0])
    """
    assert not _live(_lint(tmp_path, 'infer/engine.py', src,
                           rule='pipeline-discipline'))


def test_pipeline_discipline_ignores_non_pipeline_classes(tmp_path):
    # A class without the dispatch/consume split (the request-level
    # engine) may synchronize its own futures inline.
    src = """
        import jax
        import numpy as np

        class SimpleEngine:
            def generate(self, prompts):
                tok_dev = self._decode(prompts)
                return np.asarray(jax.device_get(tok_dev))
    """
    assert not _live(_lint(tmp_path, 'infer/engine.py', src,
                           rule='pipeline-discipline'))


def test_pipeline_discipline_scoped_to_infer(tmp_path):
    # Same code outside infer/engine.py|speculative.py: out of scope.
    assert not _live(_lint(tmp_path, 'serve/router.py',
                           _PIPELINE_DISPATCH_SYNC,
                           rule='pipeline-discipline'))


# ---------------------------------------------------------------------
# kernel-discipline
# ---------------------------------------------------------------------

_KERNEL_UNGATED = """
    import jax
    from jax.experimental import pallas as pl

    def _on_tpu():
        return jax.default_backend() == 'tpu'

    def bad_missing(x):
        return pl.pallas_call(lambda r, o: None,
                              out_shape=x)(x)

    def bad_hardcoded(x):
        return pl.pallas_call(lambda r, o: None, out_shape=x,
                              interpret=True)(x)
"""

_KERNEL_GATED = """
    import jax
    from jax.experimental import pallas as pl

    def _on_tpu():
        return jax.default_backend() == 'tpu'

    def good_direct(x):
        return pl.pallas_call(lambda r, o: None, out_shape=x,
                              interpret=not _on_tpu())(x)

    def good_default(x, interpret=None):
        return pl.pallas_call(
            lambda r, o: None, out_shape=x,
            interpret=(not _on_tpu()) if interpret is None
            else interpret)(x)
"""


def test_kernel_discipline_flags_ungated_pallas_call(tmp_path):
    findings = _live(_lint(tmp_path, 'skypilot_tpu/ops/k.py',
                           _KERNEL_UNGATED, rule='kernel-discipline'))
    assert len(findings) == 2
    assert all(f.symbol == 'pallas_call' for f in findings)
    assert any('without interpret=' in f.message for f in findings)
    assert any('does not consult _on_tpu' in f.message
               for f in findings)


def test_kernel_discipline_gated_calls_and_scope_are_clean(tmp_path):
    assert not _live(_lint(tmp_path, 'skypilot_tpu/ops/k.py',
                           _KERNEL_GATED, rule='kernel-discipline'))
    # Outside ops/ the rule does not apply — tests and benches pin
    # interpret explicitly to probe one mode.
    assert not _live(_lint(tmp_path, 'tests/unit_tests/t.py',
                           _KERNEL_UNGATED, rule='kernel-discipline'))


# ---------------------------------------------------------------------
# mesh-axis-discipline
# ---------------------------------------------------------------------

_MESH_AXIS_STRAYS = """
    import jax
    from jax.sharding import PartitionSpec as P

    from skypilot_tpu.parallel import sharding as sharding_lib

    def shard(x, mesh):
        spec = P(None, 'tp', None)              # stray alias
        y = jax.lax.psum(x, 'model')            # stray alias
        z = jax.lax.all_gather(x, axis_name='tensro')  # typo
        f = sharding_lib.shard_map_compat(
            lambda a: a, mesh=mesh, in_specs=(spec,), out_specs=spec,
            axis_names=frozenset({'head'}))     # stray axis
        return y, z, f
"""

_MESH_AXIS_CLEAN = """
    import jax
    from jax.sharding import PartitionSpec as P

    from skypilot_tpu.parallel import mesh as mesh_lib
    from skypilot_tpu.parallel import sharding as sharding_lib

    _AXIS = mesh_lib.AXIS_TENSOR

    def shard(x, mesh, axis):
        spec = P(None, 'tensor', None)          # exact constant value
        y = jax.lax.psum(x, _AXIS)              # routed via constant
        z = jax.lax.all_gather(x, axis_name=axis)  # variable: unknowable
        f = sharding_lib.shard_map_compat(
            lambda a: a, mesh=mesh, in_specs=(spec,), out_specs=spec,
            axis_names=frozenset({mesh_lib.AXIS_TENSOR}))
        return y, z, f

    MODES = ('pages', 'sequence')               # plain strings: not a call site
"""


def test_mesh_axis_discipline_flags_stray_axis_literals(tmp_path):
    findings = _live(_lint(tmp_path, 'skypilot_tpu/ops/attn.py',
                           _MESH_AXIS_STRAYS,
                           rule='mesh-axis-discipline'))
    symbols = sorted(f.symbol for f in findings)
    assert symbols == ['head', 'model', 'tensro', 'tp']
    assert all('parallel/mesh.py' in f.message for f in findings)


def test_mesh_axis_discipline_constants_and_scope_are_clean(tmp_path):
    assert not _live(_lint(tmp_path, 'skypilot_tpu/infer/engine.py',
                           _MESH_AXIS_CLEAN,
                           rule='mesh-axis-discipline'))
    # Outside ops//models//infer/ the rule does not apply — trainer
    # experiments and tests may spell ad-hoc axes.
    assert not _live(_lint(tmp_path, 'skypilot_tpu/train/t.py',
                           _MESH_AXIS_STRAYS,
                           rule='mesh-axis-discipline'))


def test_all_rule_families_are_registered():
    ids = {r.id for r in skylint.all_rules()}
    assert {'host-sync', 'retrace-hazard', 'lock-discipline',
            'thread-discipline', 'stdout-purity', 'metric-contract',
            'dtype-promotion', 'sleep-discipline',
            'net-timeout', 'trace-discipline',
            'pipeline-discipline', 'kernel-discipline',
            'mesh-axis-discipline', 'lock-order-discipline',
            'donation-discipline', 'key-reuse',
            'route-discipline', 'header-discipline',
            'status-discipline', 'env-discipline'} <= ids


# =====================================================================
# skylint 2.0: whole-program analysis
# =====================================================================

def _write_tree(tmp_path, files):
    paths = []
    for relpath, source in files.items():
        path = tmp_path / relpath
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(source))
        paths.append(str(path))
    return paths


def _lint_tree(tmp_path, files, rule=None, baseline=None):
    paths = _write_tree(tmp_path, files)
    rules = skylint.all_rules()
    if rule is not None:
        rules = [r for r in rules if r.id == rule]
        assert rules, f'unknown rule {rule}'
    return skylint.lint_files(paths, rules=rules, baseline=baseline,
                              baseline_root=str(tmp_path))


def _project(tmp_path, files):
    paths = _write_tree(tmp_path, files)
    ctxs = [skylint.FileContext(p, Path(p).read_text())
            for p in paths]
    return analysis.Project(ctxs)


# ---------------------------------------------------------------------
# analysis: module graph + call graph
# ---------------------------------------------------------------------

def test_analysis_module_names_and_import_aliases(tmp_path):
    proj = _project(tmp_path, {
        'models/m.py': """
            from utils import helpers as h

            def fwd(x):
                return h.helper_a(x)
        """,
        'utils/helpers.py': """
            def helper_a(x):
                return helper_b(x)

            def helper_b(x):
                return x
        """,
    })
    assert set(proj.modules) == {'models.m', 'utils.helpers'}
    assert proj.modules['models.m'].imports['h'] == 'utils.helpers'
    # Cross-module edge through the alias, then the local edge.
    callees = {e.callee for e in proj.calls_of('models.m.fwd')}
    assert callees == {'utils.helpers.helper_a'}
    callees = {e.callee
               for e in proj.calls_of('utils.helpers.helper_a')}
    assert callees == {'utils.helpers.helper_b'}


def test_analysis_self_dispatch_and_attr_types(tmp_path):
    proj = _project(tmp_path, {
        # The second top-level dir pins the import anchor at tmp_path,
        # so the fixture's absolute imports resolve like the repo's.
        'utils/anchor.py': '',
        'infer/eng.py': """
            from infer.pool import Pool

            class Engine:
                def __init__(self):
                    self._pool = Pool()

                def step(self):
                    self._drop()
                    self._pool.release(3)

                def _drop(self):
                    pass
        """,
        'infer/pool.py': """
            class Pool:
                def release(self, n):
                    return n
        """,
    })
    callees = {e.callee: e.via
               for e in proj.calls_of('infer.eng.Engine.step')}
    # self.method dispatch within the class...
    assert callees.get('infer.eng.Engine._drop') == 'self'
    # ...and self.attr.method through the inferred attribute type,
    # minus the Pool() constructor edge.
    assert callees.get('infer.pool.Pool.release') == 'self'


def test_analysis_partial_prebinding_arg_offsets(tmp_path):
    proj = _project(tmp_path, {
        'models/p.py': """
            import functools

            def consume(scale, n):
                return scale * n

            def outer(n):
                f = functools.partial(consume, 2.0)
                return f(n)
        """,
    })
    (outer_q,) = [q for q in proj.functions if q.endswith('outer')]
    edges = {(e.callee.rsplit('.', 1)[-1], e.via, e.arg_offset)
             for e in proj.calls_of(outer_q)}
    # The partial() site itself (args shift -1) and the bound-local
    # call (args shift +1 past the pre-bound scale).
    assert ('consume', 'partial', -1) in edges
    assert ('consume', 'partial', 1) in edges


def test_analysis_single_parse_per_file(tmp_path):
    files = {
        'models/a.py': 'import jax\nx = 1\n',
        'models/b.py': 'y = 2\n',
        'utils/c.py': 'z = 3\n',
    }
    paths = _write_tree(tmp_path, files)
    before = skylint.PARSE_COUNT
    findings = skylint.lint_files(paths, rules=skylint.all_rules())
    assert skylint.PARSE_COUNT - before == len(paths), \
        'whole-program linting must parse each file exactly once'
    assert not _live(findings)


# ---------------------------------------------------------------------
# host-sync 2.0: interprocedural
# ---------------------------------------------------------------------

_JIT_CALLS_HELPER = """
    import jax
    from utils import helpers as h

    @jax.jit
    def fwd(x):
        h.helper_a(x)
        return x
"""

_HELPERS_TWO_HOP = """
    import time

    def helper_a(x):
        return helper_b(x)

    def helper_b(x):
        t = time.time()
        return x, t
"""


def test_host_sync_transitive_two_hop_chain(tmp_path):
    findings = _live(_lint_tree(tmp_path, {
        'models/m.py': _JIT_CALLS_HELPER,
        'utils/helpers.py': _HELPERS_TWO_HOP,
    }, rule='host-sync'))
    assert len(findings) == 1
    f = findings[0]
    assert f.symbol == 'time.time()'
    # Anchored at the jit-body call site, not in utils/.
    assert f.path.endswith('models/m.py')
    # Chain: jit entry -> helper_a -> helper_b -> the syncing call.
    assert len(f.call_chain) == 4
    assert 'helper_a' in f.call_chain[1]
    assert 'helper_b' in f.call_chain[2]
    assert f.call_chain[-1] == 'time.time()'


def test_host_sync_single_file_pass_provably_misses_it(tmp_path):
    # The same jit body linted WITHOUT the helper module on the scan
    # list: the hazard lives two modules away, and a per-file pass
    # (pre-2.0 behavior) has nothing to resolve the call against.
    assert not _live(_lint_tree(tmp_path, {
        'models/m.py': _JIT_CALLS_HELPER,
    }, rule='host-sync'))
    # With the helper scanned, the exact same file flags (see
    # test_host_sync_transitive_two_hop_chain) — the delta IS the
    # whole-program index.


# ---------------------------------------------------------------------
# retrace 2.0: taint through calls
# ---------------------------------------------------------------------

def test_retrace_transitive_through_helper_module(tmp_path):
    findings = _live(_lint_tree(tmp_path, {
        'models/m.py': """
            import jax
            from utils import shapes as sh

            def _decode(logits, top_k):
                return sh.trim(logits, top_k)

            decode = jax.jit(_decode)
        """,
        'utils/shapes.py': """
            import jax.numpy as jnp

            def trim(logits, k):
                if k > 0:
                    return jnp.zeros((k,))
                return logits
        """,
    }, rule='retrace-hazard'))
    assert len(findings) == 1
    f = findings[0]
    assert f.symbol == '_decode.top_k'
    assert f.path.endswith('models/m.py')
    assert any('trim' in hop for hop in f.call_chain)


def test_retrace_transitive_through_partial_and_self(tmp_path):
    findings = _live(_lint_tree(tmp_path, {
        'models/m.py': """
            import functools
            import jax

            def consume(scale, k):
                return list(range(k))

            class Decoder:
                def __init__(self):
                    def _fwd(x, top_k):
                        return self._trim(x, top_k)

                    self._step = jax.jit(_fwd)

                def _trim(self, x, k):
                    f = functools.partial(consume, 2.0)
                    return f(k)
        """,
    }, rule='retrace-hazard'))
    assert len(findings) == 1
    # Taint flows _fwd.top_k -> (self dispatch, +1 for the bound
    # receiver) _trim.k -> (partial, pre-bound scale skipped)
    # consume.k -> range(k).
    assert findings[0].symbol == '_fwd.top_k'


def test_retrace_static_param_stays_clean_through_calls(tmp_path):
    assert not _live(_lint_tree(tmp_path, {
        'models/m.py': """
            import jax
            from utils import shapes as sh

            def _decode(logits, top_k):
                return sh.trim(logits, top_k)

            decode = jax.jit(_decode, static_argnames=('top_k',))
        """,
        'utils/shapes.py': """
            import jax.numpy as jnp

            def trim(logits, k):
                if k > 0:
                    return jnp.zeros((k,))
                return logits
        """,
    }, rule='retrace-hazard'))


# ---------------------------------------------------------------------
# lock-order-discipline
# ---------------------------------------------------------------------

def test_lock_order_flags_ab_ba_cycle(tmp_path):
    findings = _live(_lint_tree(tmp_path, {
        'infer/paging.py': """
            import threading

            class Pool:
                def __init__(self):
                    self._alloc_lock = threading.Lock()
                    self._table_lock = threading.Lock()

                def grow(self):
                    with self._alloc_lock:
                        with self._table_lock:
                            pass

                def shrink(self):
                    with self._table_lock:
                        with self._alloc_lock:
                            pass
        """,
    }, rule='lock-order-discipline'))
    assert len(findings) == 1
    f = findings[0]
    assert f.symbol.startswith('cycle:')
    assert 'Pool._alloc_lock' in f.message
    assert 'Pool._table_lock' in f.message
    assert len(f.call_chain) >= 2


def test_lock_order_cycle_through_call_graph(tmp_path):
    # Engine holds its lock and calls the allocator (which takes the
    # allocator lock); the allocator holds its lock and calls back
    # into the engine.  Neither file looks wrong alone.
    findings = _live(_lint_tree(tmp_path, {
        'utils/anchor.py': '',
        'infer/eng.py': """
            import threading
            from infer.alloc import Alloc

            class Engine:
                def __init__(self):
                    self._submit_lock = threading.Lock()
                    self._alloc = Alloc()

                def submit(self):
                    with self._submit_lock:
                        self._alloc.reserve(1)

                def wake(self):
                    with self._submit_lock:
                        pass
        """,
        'infer/alloc.py': """
            import threading
            from infer.eng import Engine

            class Alloc:
                def __init__(self):
                    self._alloc_lock = threading.Lock()
                    self.eng = Engine()

                def reserve(self, n):
                    with self._alloc_lock:
                        return n

                def evict(self):
                    with self._alloc_lock:
                        self.eng.wake()
        """,
    }, rule='lock-order-discipline'))
    assert len(findings) == 1
    f = findings[0]
    assert 'Engine._submit_lock' in f.message
    assert 'Alloc._alloc_lock' in f.message


def test_lock_order_consistent_nesting_is_clean(tmp_path):
    # A -> B in two places, never B -> A: a hierarchy, not a cycle.
    assert not _live(_lint_tree(tmp_path, {
        'infer/paging.py': """
            import threading

            class Pool:
                def __init__(self):
                    self._alloc_lock = threading.Lock()
                    self._table_lock = threading.Lock()

                def grow(self):
                    with self._alloc_lock:
                        with self._table_lock:
                            pass

                def shrink(self):
                    with self._alloc_lock:
                        with self._table_lock:
                            pass
        """,
    }, rule='lock-order-discipline'))


def test_lock_order_check_then_act_and_dcl_exemption(tmp_path):
    findings = _live(_lint_tree(tmp_path, {
        'serve/cache.py': """
            import threading

            class Cache:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._entries = None

                def fill(self, v):
                    with self._lock:
                        self._entries = v

                def racy_get(self):
                    if self._entries is None:
                        with self._lock:
                            self._entries = []
                    return self._entries

                def dcl_get(self):
                    if self._entries is None:
                        with self._lock:
                            if self._entries is None:
                                self._entries = []
                    return self._entries
        """,
    }, rule='lock-order-discipline'))
    assert len(findings) == 1
    f = findings[0]
    assert f.symbol == 'Cache._entries'
    assert 'check-then-act' in f.message
    assert 'racy_get' in f.message


def test_lock_order_scoped_to_serving_packages(tmp_path):
    assert not _live(_lint_tree(tmp_path, {
        'provision/x.py': """
            import threading

            class P:
                def __init__(self):
                    self._a_lock = threading.Lock()
                    self._b_lock = threading.Lock()

                def f(self):
                    with self._a_lock:
                        with self._b_lock:
                            pass

                def g(self):
                    with self._b_lock:
                        with self._a_lock:
                            pass
        """,
    }, rule='lock-order-discipline'))


# ---------------------------------------------------------------------
# donation-discipline
# ---------------------------------------------------------------------

def test_donation_flags_read_after_donated_call(tmp_path):
    findings = _live(_lint_tree(tmp_path, {
        'infer/eng.py': """
            import jax

            def _step(cache, tok):
                return cache

            class Engine:
                def __init__(self):
                    self._step = jax.jit(_step, donate_argnums=(0,))

                def run(self, cache, tok):
                    out = self._step(cache, tok)
                    return cache
        """,
    }, rule='donation-discipline'))
    assert len(findings) == 1
    f = findings[0]
    assert 'use-after-donate' in f.message
    assert len(f.call_chain) == 2


def test_donation_rebound_result_and_argnames_are_clean(tmp_path):
    assert not _live(_lint_tree(tmp_path, {
        'infer/eng.py': """
            import jax

            def _step(cache, tok):
                return cache

            class Engine:
                def __init__(self):
                    self._step = jax.jit(_step,
                                         donate_argnames=('cache',))

                def run(self, cache, tok):
                    cache = self._step(cache, tok)
                    return cache
        """,
    }, rule='donation-discipline'))


def test_donation_argnames_matches_keyword_call_site(tmp_path):
    findings = _live(_lint_tree(tmp_path, {
        'infer/eng.py': """
            import jax

            def _step(cache, tok):
                return cache

            run_step = jax.jit(_step, donate_argnames=('cache',))

            def drive(cache, tok):
                out = run_step(tok=tok, cache=cache)
                return cache.mean()
        """,
    }, rule='donation-discipline'))
    assert len(findings) == 1
    assert 'cache' in findings[0].symbol


# ---------------------------------------------------------------------
# key-reuse
# ---------------------------------------------------------------------

def test_key_reuse_flags_double_consumption_via_alias(tmp_path):
    findings = _live(_lint_tree(tmp_path, {
        'models/sampling.py': """
            from jax import random as jr

            def sample_two(logits, key):
                a = jr.categorical(key, logits)
                b = jr.categorical(key, logits)
                return a, b
        """,
    }, rule='key-reuse'))
    assert len(findings) == 1
    f = findings[0]
    assert f.symbol == 'sample_two.key'
    assert len(f.call_chain) == 2


def test_key_reuse_split_and_fold_in_are_clean(tmp_path):
    assert not _live(_lint_tree(tmp_path, {
        'models/sampling.py': """
            import jax

            def sample_ok(logits, key):
                k1, k2 = jax.random.split(key)
                a = jax.random.categorical(k1, logits)
                b = jax.random.categorical(k2, logits)
                return a, b

            def per_lane(logits, key, n):
                outs = []
                for i in range(n):
                    sub = jax.random.fold_in(key, i)
                    outs.append(jax.random.categorical(sub, logits))
                return outs
        """,
    }, rule='key-reuse'))


def test_key_reuse_catches_unrefreshed_loop_key(tmp_path):
    findings = _live(_lint_tree(tmp_path, {
        'models/sampling.py': """
            import jax

            def sample_loop(logits, key, n):
                outs = []
                for _ in range(n):
                    outs.append(jax.random.categorical(key, logits))
                return outs
        """,
    }, rule='key-reuse'))
    assert len(findings) == 1
    assert findings[0].symbol == 'sample_loop.key'


def test_key_reuse_exclusive_branches_are_clean(tmp_path):
    assert not _live(_lint_tree(tmp_path, {
        'models/sampling.py': """
            import jax

            def sample(logits, key, greedy):
                if greedy:
                    return jax.random.categorical(key, logits)
                else:
                    return jax.random.gumbel(key, logits.shape)
        """,
    }, rule='key-reuse'))


# ---------------------------------------------------------------------
# JSON schema 2.0: call_chain + fingerprint; baseline v2
# ---------------------------------------------------------------------

def test_json_carries_call_chain_and_fingerprint(tmp_path, capsys):
    _write_tree(tmp_path, {
        'models/m.py': _JIT_CALLS_HELPER,
        'utils/helpers.py': _HELPERS_TWO_HOP,
    })
    rc = skylint.main(['--format', 'json', '--no-baseline',
                       '--rule', 'host-sync', str(tmp_path)])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    (finding,) = doc['findings']
    assert finding['symbol'] == 'time.time()'
    assert isinstance(finding['call_chain'], list)
    assert len(finding['call_chain']) == 4
    fp = finding['fingerprint']
    assert fp and len(fp) == 12
    # Fingerprints hash rule|path-relative-to-baseline-root|symbol
    # (cwd when --no-baseline), stable across line drift.
    rel = os.path.relpath(os.path.abspath(finding['path']),
                          os.getcwd()).replace(os.sep, '/')
    assert fp == skylint.fingerprint_of('host-sync', rel,
                                        finding['symbol'])


def test_baseline_fingerprint_entry_suppresses(tmp_path):
    files = {
        'models/m.py': _JIT_CALLS_HELPER,
        'utils/helpers.py': _HELPERS_TWO_HOP,
    }
    (live,) = _live(_lint_tree(tmp_path, files, rule='host-sync'))
    entry = skylint.BaselineEntry(rule='', path_glob='',
                                  symbol_glob='',
                                  fingerprint=live.fingerprint)
    findings = _lint_tree(tmp_path, files, rule='host-sync',
                          baseline=[entry])
    flagged = [f for f in findings if f.rule == 'host-sync']
    assert flagged and all(f.suppressed for f in flagged)
    assert flagged[0].suppressed_by == 'baseline'


def test_load_baseline_parses_fingerprint_lines(tmp_path):
    bl = tmp_path / '.skylint-baseline'
    bl.write_text('# v2\n'
                  'stdout-purity:legacy/*.py:*\n'
                  'fingerprint:abcdef012345\n')
    entries = skylint.load_baseline(str(bl))
    assert len(entries) == 2
    assert entries[0].rule == 'stdout-purity'
    assert entries[1].fingerprint == 'abcdef012345'


# ---------------------------------------------------------------------
# --changed-only
# ---------------------------------------------------------------------

def test_changed_only_filters_findings_but_keeps_index(tmp_path):
    paths = _write_tree(tmp_path, {
        'models/m.py': _JIT_CALLS_HELPER,
        'utils/helpers.py': _HELPERS_TWO_HOP,
    })
    env = {'GIT_AUTHOR_NAME': 't', 'GIT_AUTHOR_EMAIL': 't@t',
           'GIT_COMMITTER_NAME': 't', 'GIT_COMMITTER_EMAIL': 't@t',
           'HOME': str(tmp_path), 'PATH': os.environ['PATH']}
    run = lambda *args: subprocess.run(
        args, cwd=str(tmp_path), env=env, check=True,
        capture_output=True)
    run('git', 'init', '-q')
    run('git', 'add', '-A')
    run('git', 'commit', '-qm', 'seed')
    # Touch ONLY the jit-side file; the helper is unchanged.
    (tmp_path / 'models' / 'm.py').write_text(
        textwrap.dedent(_JIT_CALLS_HELPER) + '\n# touched\n')
    cwd = os.getcwd()
    os.chdir(str(tmp_path))
    try:
        findings = skylint.lint_paths(
            ['.'], rule_ids=['host-sync'], use_baseline=False,
            changed_only='HEAD')
        live = _live(findings)
        # The transitive finding (which NEEDS the unchanged helper in
        # the index) survives, anchored in the changed file...
        assert len(live) == 1
        assert live[0].path.endswith('models/m.py')
        # ...and with nothing changed, nothing is reported.
        run('git', 'add', '-A')
        run('git', 'commit', '-qm', 'touch')
        findings = skylint.lint_paths(
            ['.'], rule_ids=['host-sync'], use_baseline=False,
            changed_only='HEAD')
        assert not _live(findings)
    finally:
        os.chdir(cwd)


# =====================================================================
# skylint 3.0: cross-process protocol analysis
# =====================================================================

# Canonical guarded wire server: serves GET /health and POST /generate
# (both in ROUTE_CONTRACT) and answers wrong-method hits with
# 405+Allow, so route-discipline fixtures can isolate one defect at a
# time.
_WIRE_SERVER = """
    _POST_ROUTES = ('/generate',)

    class Handler:
        def _reply(self, code, body, allow=None):
            self.send_response(code)

        def do_GET(self):
            route = self.path
            if route == '/health':
                up = self.up
                code = 200 if up else 503
                self._reply(code, {})
            elif route in _POST_ROUTES:
                self._reply(405, {}, allow='POST')
            else:
                self._reply(404, {})

        def do_POST(self):
            route = self.path
            if route not in _POST_ROUTES:
                self._reply(405, {}, allow='GET')
                return
            self._reply(200, {})
"""

_WIRE_CLIENT = """
    import urllib.request

    def fire(base, body):
        req = urllib.request.Request(base + '{path}', data=body,
                                     method='POST')
        return urllib.request.urlopen(req, timeout=5)
"""


# ---------------------------------------------------------------------
# route-discipline
# ---------------------------------------------------------------------

def test_route_discipline_contract_pair_is_clean(tmp_path):
    findings = _live(_lint_tree(tmp_path, {
        'utils/anchor.py': '',
        'serve/rt.py': _WIRE_SERVER,
        'benchmark/cli.py': _WIRE_CLIENT.format(path='/generate'),
    }, rule='route-discipline'))
    assert not findings, [f.render() for f in findings]


def test_route_discipline_mutation_renamed_client_path(tmp_path):
    # THE cross-file case the old per-file pass cannot see: rename the
    # client's spelling of a contract route and exactly one finding
    # appears, whose call chain crosses into the server file that
    # still serves the old spelling.
    findings = _live(_lint_tree(tmp_path, {
        'utils/anchor.py': '',
        'serve/rt.py': _WIRE_SERVER,
        'benchmark/cli.py': _WIRE_CLIENT.format(path='/generat'),
    }, rule='route-discipline'))
    assert len(findings) == 1, [f.render() for f in findings]
    f = findings[0]
    assert f.symbol == 'POST /generat'
    assert f.path.endswith('cli.py')
    assert any('rt.py' in hop and '/generate' in hop
               for hop in f.call_chain), f.call_chain
    assert f.fingerprint


def test_route_discipline_flags_server_route_not_in_contract(
        tmp_path):
    src = _WIRE_SERVER.replace(
        "if route == '/health':",
        "if route == '/bogus_route':\n"
        "                self._reply(200, {})\n"
        "            elif route == '/health':")
    findings = _live(_lint_tree(tmp_path, {
        'utils/anchor.py': '',
        'serve/rt.py': src,
    }, rule='route-discipline'))
    assert {f.symbol for f in findings} == {'GET /bogus_route'}
    assert 'ROUTE_CONTRACT' in findings[0].message


def test_route_discipline_flags_missing_405_guard(tmp_path):
    findings = _live(_lint_tree(tmp_path, {
        'utils/anchor.py': '',
        'serve/rt.py': """
            class Handler:
                def _reply(self, code, body):
                    self.send_response(code)

                def do_GET(self):
                    route = self.path
                    if route == '/health':
                        self._reply(200, {})
                    else:
                        self._reply(404, {})
        """,
    }, rule='route-discipline'))
    assert {f.symbol for f in findings} == {'POST-405-guard'}
    assert 'Allow' in findings[0].message


def test_route_discipline_dynamic_paths_and_scope_are_clean(
        tmp_path):
    # A fully dynamic client (path and method from variables) matches
    # whatever the caller passes; devtools code is out of scope.
    findings = _live(_lint_tree(tmp_path, {
        'utils/anchor.py': '',
        'serve/dyn.py': """
            import urllib.request

            def forward(url, body, method):
                req = urllib.request.Request(url, data=body,
                                             method=method)
                return urllib.request.urlopen(req, timeout=5)
        """,
        'devtools/fetch.py': """
            import urllib.request

            def grab(base):
                return urllib.request.urlopen(base + '/not_a_route',
                                              timeout=5)
        """,
    }, rule='route-discipline'))
    assert not findings, [f.render() for f in findings]


# ---------------------------------------------------------------------
# header-discipline
# ---------------------------------------------------------------------

def test_header_discipline_paired_contract_header_is_clean(tmp_path):
    findings = _live(_lint_tree(tmp_path, {
        'utils/anchor.py': '',
        'serve/a.py': """
            TRACE_HEADER = 'X-Skytpu-Trace'

            class H:
                def stamp(self):
                    self.send_header(TRACE_HEADER, 'tid')
        """,
        'serve/b.py': """
            class R:
                def read(self):
                    return self.headers.get('X-Skytpu-Trace')
        """,
    }, rule='header-discipline'))
    assert not findings, [f.render() for f in findings]


def test_header_discipline_mutation_renamed_reader_side(tmp_path):
    # Rename the reading side's literal: the read becomes an unknown
    # fleet-namespace header AND the stamp in the OTHER file becomes
    # stamped-but-never-read — both sides of the drift are named.
    findings = _live(_lint_tree(tmp_path, {
        'utils/anchor.py': '',
        'serve/a.py': """
            TRACE_HEADER = 'X-Skytpu-Trace'

            class H:
                def stamp(self):
                    self.send_header(TRACE_HEADER, 'tid')
        """,
        'serve/b.py': """
            class R:
                def read(self):
                    return self.headers.get('X-Skytpu-Tracing')
        """,
    }, rule='header-discipline'))
    assert {f.symbol for f in findings} == {'X-Skytpu-Tracing',
                                            'X-Skytpu-Trace'}
    by_symbol = {f.symbol: f for f in findings}
    assert by_symbol['X-Skytpu-Tracing'].path.endswith('b.py')
    stale = by_symbol['X-Skytpu-Trace']
    assert stale.path.endswith('a.py')
    assert 'never read' in stale.message
    assert any('a.py' in hop for hop in stale.call_chain)


def test_header_discipline_read_without_stamp_is_flagged(tmp_path):
    findings = _live(_lint_tree(tmp_path, {
        'utils/anchor.py': '',
        'infer/srv.py': """
            class H:
                def read(self):
                    return self.headers.get('X-Skytpu-Decode-Target')
        """,
    }, rule='header-discipline'))
    assert len(findings) == 1
    assert findings[0].symbol == 'X-Skytpu-Decode-Target'
    assert 'never stamped' in findings[0].message


def test_header_discipline_scope_and_non_fleet_names(tmp_path):
    findings = _live(_lint_tree(tmp_path, {
        'utils/anchor.py': '',
        # Non-fleet headers are never checked ...
        'serve/a.py': """
            class H:
                def stamp(self):
                    self.send_header('Content-Type', 'text/html')
        """,
        # ... and devtools code is outside the wire scope even for
        # fleet-namespace names.
        'devtools/x.py': """
            class H:
                def stamp(self):
                    self.send_header('X-Skytpu-Whatever', '1')
        """,
    }, rule='header-discipline'))
    assert not findings, [f.render() for f in findings]


# ---------------------------------------------------------------------
# status-discipline
# ---------------------------------------------------------------------

def test_status_discipline_branched_client_is_clean(tmp_path):
    findings = _live(_lint_tree(tmp_path, {
        'utils/anchor.py': '',
        'serve/rt.py': _WIRE_SERVER,
        'benchmark/cli.py': """
            import urllib.error
            import urllib.request

            def probe(base):
                try:
                    return urllib.request.urlopen(base + '/health',
                                                  timeout=1)
                except urllib.error.HTTPError as e:
                    return e.code == 503
        """,
    }, rule='status-discipline'))
    assert not findings, [f.render() for f in findings]


def test_status_discipline_flags_unhandled_branch_status(tmp_path):
    # /health's 503 is branch-required (it is the shed/drain signal);
    # a client that folds it into a generic error path loses the
    # distinction.  The chain names the server line that emits it.
    findings = _live(_lint_tree(tmp_path, {
        'utils/anchor.py': '',
        'serve/rt.py': _WIRE_SERVER,
        'benchmark/cli.py': """
            import urllib.error
            import urllib.request

            def probe(base):
                try:
                    return urllib.request.urlopen(base + '/health',
                                                  timeout=1)
                except urllib.error.HTTPError:
                    return None
        """,
    }, rule='status-discipline'))
    assert len(findings) == 1, [f.render() for f in findings]
    f = findings[0]
    assert f.symbol == 'GET /health 503'
    assert f.path.endswith('cli.py')
    assert any('rt.py' in hop and 'emits 503' in hop
               for hop in f.call_chain), f.call_chain


def test_status_discipline_flags_fail_closed_swallow(tmp_path):
    # The _relay_handoff shape: Request built outside the try, urlopen
    # inside an `except URLError: continue` peer loop.  HTTPError
    # subclasses URLError, so a terminal 409 is silently retried.
    findings = _live(_lint_tree(tmp_path, {
        'utils/anchor.py': '',
        'infer/relay.py': """
            import urllib.error
            import urllib.request

            def relay(targets, blob):
                for t in targets:
                    req = urllib.request.Request(
                        t + '/handoff', data=blob, method='POST')
                    try:
                        return urllib.request.urlopen(req, timeout=5)
                    except (urllib.error.URLError, OSError):
                        continue
        """,
    }, rule='status-discipline'))
    swallow = [f for f in findings if 'subclasses URLError'
               in f.message]
    assert {f.symbol for f in swallow} == {'POST /handoff 400',
                                           'POST /handoff 409'}


def test_status_discipline_flags_retry_classifier_admitting_409(
        tmp_path):
    findings = _live(_lint_tree(tmp_path, {
        'utils/anchor.py': '',
        'infer/push.py': """
            import urllib.error
            import urllib.request

            _RETRY_CODES = (409, 500)

            def push(base, blob):
                req = urllib.request.Request(
                    base + '/handoff', data=blob, method='POST')
                try:
                    return urllib.request.urlopen(req, timeout=5)
                except urllib.error.HTTPError as e:
                    if e.code in (400, 503):
                        raise
                    if e.code in _RETRY_CODES:
                        return push(base, blob)
                    raise
        """,
    }, rule='status-discipline'))
    assert len(findings) == 1, [f.render() for f in findings]
    assert findings[0].symbol == 'POST /handoff 409'
    assert 'retry classifier' in findings[0].message


def test_status_discipline_fail_closed_terminal_client_is_clean(
        tmp_path):
    findings = _live(_lint_tree(tmp_path, {
        'utils/anchor.py': '',
        'infer/push.py': """
            import urllib.error
            import urllib.request

            _RETRY_CODES = (500, 502)

            def push(base, blob):
                req = urllib.request.Request(
                    base + '/handoff', data=blob, method='POST')
                try:
                    return urllib.request.urlopen(req, timeout=5)
                except urllib.error.HTTPError as e:
                    if e.code in (400, 409, 503):
                        raise
                    if e.code in _RETRY_CODES:
                        return push(base, blob)
                    raise
        """,
    }, rule='status-discipline'))
    assert not findings, [f.render() for f in findings]


# ---------------------------------------------------------------------
# env-discipline
# ---------------------------------------------------------------------

def test_env_discipline_flags_unregistered_var(tmp_path):
    findings = _live(_lint(tmp_path, 'utils/cfg.py', """
        import os

        def n():
            return os.environ.get('SKYTPU_NO_SUCH_VAR', '')
    """, rule='env-discipline'))
    assert len(findings) == 1
    assert findings[0].symbol == 'SKYTPU_NO_SUCH_VAR'
    assert 'ENV_CONTRACT' in findings[0].message


def test_env_discipline_flags_divergent_inline_default(tmp_path):
    # The repo's own historical drift: the int 1800 vs the contract's
    # '1800' — same value today, silently divergent on the next edit.
    findings = _live(_lint(tmp_path, 'provision/x.py', """
        import os

        def t():
            return float(os.environ.get('SKYTPU_QUEUED_TIMEOUT',
                                        1800))
    """, rule='env-discipline'))
    assert len(findings) == 1
    assert findings[0].symbol == 'SKYTPU_QUEUED_TIMEOUT'
    assert "'1800'" in findings[0].message


def test_env_discipline_flags_missing_inline_default(tmp_path):
    findings = _live(_lint(tmp_path, 'utils/cfg.py', """
        import os

        def t():
            return os.getenv('SKYTPU_QUEUED_TIMEOUT')
    """, rule='env-discipline'))
    assert len(findings) == 1
    assert 'no inline default' in findings[0].message


def test_env_discipline_matching_and_exempt_reads_are_clean(
        tmp_path):
    findings = _live(_lint(tmp_path, 'utils/cfg.py', """
        import os

        def t():
            # matches the contract default exactly
            a = os.environ.get('SKYTPU_QUEUED_TIMEOUT', '1800')
            # contract default None (unset-disables): no comparison
            b = os.environ.get('SKYTPU_HANDOFF_COMPRESS')
            # not a SKYTPU_* name: out of scope
            c = os.environ.get('HOME', '/root')
            # computed default expressions are not comparable
            d = os.environ.get('SKYTPU_QUEUED_TIMEOUT', default())
            return a, b, c, d

        def default():
            return '1800'
    """, rule='env-discipline'))
    assert not findings, [f.render() for f in findings]


def test_net_timeout_applies_to_bench_entrypoint(tmp_path):
    # Satellite of the protocol PR: bench.py drives the same wire
    # surface; its blocking calls wedge the bench run the same way.
    assert _live(_lint(tmp_path, 'bench.py', _NET_NO_TIMEOUT,
                       rule='net-timeout'))
    assert not _live(_lint(tmp_path, 'bench.py', _NET_WITH_TIMEOUT,
                           rule='net-timeout'))
