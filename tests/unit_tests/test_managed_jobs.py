"""Managed-jobs tests: lifecycle, preemption recovery, restarts, cancel.

Hermetic analog of the reference's managed-job smoke tests
(tests/smoke_tests/test_managed_job.py — which induce preemption by
*really terminating cloud instances*): here the task clusters are local
process clusters and preemption = terminating the cluster's instances
through the provisioner API out from under the controller.
"""
import os
import time

import pytest

import skypilot_tpu as sky
from skypilot_tpu import global_user_state
from skypilot_tpu import jobs
from skypilot_tpu.jobs import state as jobs_state
from skypilot_tpu.provision.local import instance as local_instance


@pytest.fixture(autouse=True)
def _fast_loops(monkeypatch):
    monkeypatch.setenv('SKYTPU_JOBS_STATUS_GAP', '0.3')
    monkeypatch.setenv('SKYTPU_JOBS_LAUNCH_BACKOFF', '0.2')
    yield
    # Cancel anything still alive, then join controller threads so they
    # cannot write into the next test's state dir.
    from skypilot_tpu.jobs import controller as controller_lib
    try:
        jobs.cancel(all_jobs=True)
    except Exception:  # noqa: BLE001
        pass
    controller_lib.join_all_controller_threads(60)


def _local_task(run, name=None, **kwargs):
    t = sky.Task(name=name, run=run)
    t.set_resources(sky.Resources(cloud='local', **kwargs))
    return t


def _load_factor() -> float:
    """Suite-load-aware timeout scaling (round-3 verdict: the recovery
    capstone passes isolated in ~1 min but timed out under the full
    26-minute suite's machine load).  Timeouts are budgets, not
    expectations — a green run never waits them out — so scale them
    up when the 1-minute load average exceeds the core count."""
    try:
        per_core = os.getloadavg()[0] / max(os.cpu_count() or 1, 1)
    except OSError:
        return 1.0
    return min(4.0, max(1.0, per_core))


def _wait(pred, timeout=60, gap=0.2, desc='condition'):
    deadline = time.time() + timeout * _load_factor()
    while time.time() < deadline:
        if pred():
            return
        time.sleep(gap)
    raise TimeoutError(f'Timed out waiting for {desc}.')


def _task_row(job_id, task_id=0):
    return jobs_state.get_job_tasks(job_id)[task_id]


class TestManagedJobs:

    def test_job_succeeds_and_cleans_up(self):
        job_id = jobs.launch(_local_task('echo managed-ok', name='mj1'),
                             controller_mode='thread')
        status = jobs.wait(job_id, timeout=90)
        assert status == jobs.ManagedJobStatus.SUCCEEDED
        row = _task_row(job_id)
        assert row['recovery_count'] == 0
        # Task cluster is torn down after success.
        _wait(lambda: global_user_state.get_cluster_from_name(
            row['cluster_name']) is None, desc='cluster teardown')

    def test_queue_and_get_status(self):
        job_id = jobs.launch(_local_task('echo q', name='mjq'),
                             controller_mode='thread')
        rows = jobs.queue()
        assert any(r['job_id'] == job_id for r in rows)
        # Generous: under a fully loaded suite the thread controller's
        # launch+probe loop can lag well past the usual few seconds
        # (flaked at 150s once the suite passed 400 tests).
        jobs.wait(job_id, timeout=300)
        assert jobs.get_status(job_id) == jobs.ManagedJobStatus.SUCCEEDED
        # The scheduler flips ALIVE -> DONE shortly AFTER the job
        # reaches terminal status; don't assert the transition
        # instantly.
        _wait(lambda: jobs_state.get_job_info(job_id)['schedule_state']
              == jobs_state.ScheduleState.DONE, timeout=60,
              desc='schedule_state DONE')

    def test_user_failure_not_recovered(self):
        job_id = jobs.launch(_local_task('exit 1', name='mjf'),
                             controller_mode='thread')
        status = jobs.wait(job_id, timeout=90)
        assert status == jobs.ManagedJobStatus.FAILED
        assert _task_row(job_id)['recovery_count'] == 0

    def test_max_restarts_on_errors(self):
        t = _local_task('exit 1', name='mjr',
                        job_recovery={'strategy': 'FAILOVER',
                                      'max_restarts_on_errors': 1})
        job_id = jobs.launch(t, controller_mode='thread')
        status = jobs.wait(job_id, timeout=120)
        assert status == jobs.ManagedJobStatus.FAILED
        # One restart was consumed: the task was relaunched exactly once.
        assert _task_row(job_id)['recovery_count'] == 1

    def test_preemption_recovery(self):
        # Long-running job; we preempt its cluster mid-flight.
        job_id = jobs.launch(_local_task('sleep 600', name='mjp'),
                             controller_mode='thread')
        _wait(lambda: _task_row(job_id)['status'] ==
              jobs.ManagedJobStatus.RUNNING, timeout=90, desc='RUNNING')
        cluster_name = _task_row(job_id)['cluster_name']
        record = global_user_state.get_cluster_from_name(cluster_name)
        assert record is not None
        handle = record['handle']
        # Preemption: the provider terminates the instances externally.
        local_instance.terminate_instances(handle.cluster_name_on_cloud)
        _wait(lambda: _task_row(job_id)['recovery_count'] >= 1,
              timeout=120, desc='recovery')
        _wait(lambda: _task_row(job_id)['status'] ==
              jobs.ManagedJobStatus.RUNNING, timeout=90,
              desc='RUNNING after recovery')
        # New cluster is a different incarnation and is UP.
        rec2 = global_user_state.get_cluster_from_name(cluster_name)
        assert rec2 is not None
        assert rec2['status'] == global_user_state.ClusterStatus.UP
        jobs.cancel([job_id])
        jobs.wait(job_id, timeout=90)

    def test_cancel(self):
        job_id = jobs.launch(_local_task('sleep 600', name='mjc'),
                             controller_mode='thread')
        _wait(lambda: _task_row(job_id)['status'] ==
              jobs.ManagedJobStatus.RUNNING, timeout=90, desc='RUNNING')
        cancelled = jobs.cancel([job_id])
        assert cancelled == [job_id]
        status = jobs.wait(job_id, timeout=90)
        assert status == jobs.ManagedJobStatus.CANCELLED
        row = _task_row(job_id)
        _wait(lambda: global_user_state.get_cluster_from_name(
            row['cluster_name']) is None, desc='cluster teardown')

    def test_pipeline_chain(self):
        a = _local_task('echo stage-a', name='stage-a')
        b = _local_task('echo stage-b', name='stage-b')
        with sky.Dag() as d:
            d.add(a)
            d.add(b)
            d.add_edge(a, b)
        d.name = 'mj-pipe'
        job_id = jobs.launch(d, controller_mode='thread')
        status = jobs.wait(job_id, timeout=180)
        assert status == jobs.ManagedJobStatus.SUCCEEDED
        rows = jobs_state.get_job_tasks(job_id)
        assert len(rows) == 2
        assert all(r['status'] == jobs.ManagedJobStatus.SUCCEEDED
                   for r in rows)

    def test_cancel_by_name_and_unknown(self):
        with pytest.raises(Exception):
            jobs.cancel(name='no-such-job')

    def test_setup_failure_fails_fast(self):
        t = sky.Task(name='mjs', run='echo never', setup='exit 1')
        t.set_resources(sky.Resources(cloud='local'))
        job_id = jobs.launch(t, controller_mode='thread')
        status = jobs.wait(job_id, timeout=90)
        assert status == jobs.ManagedJobStatus.FAILED_SETUP
        # No recovery attempts for setup failures.
        assert _task_row(job_id)['recovery_count'] == 0

    def test_process_mode_controller(self):
        job_id = jobs.launch(_local_task('echo proc-mode', name='mjproc'),
                             controller_mode='process')
        status = jobs.wait(job_id, timeout=120)
        assert status == jobs.ManagedJobStatus.SUCCEEDED
        assert jobs_state.get_job_info(job_id)['controller_pid'] is not None


class TestTrainerRecoveryCapstone:

    @pytest.mark.slow  # CPU tier-1 budget: full trainer/engine run
    def test_preempted_training_job_resumes_from_checkpoint(
            self, tmp_path):
        """The marquee TPU-recovery story end-to-end: a REAL trainer
        job checkpoints to a shared dir, its cluster is preempted, the
        controller relaunches it, and the recovered run RESUMES from
        the checkpoint (restored step visible in the new incarnation's
        log) instead of restarting from zero."""
        ckpt = str(tmp_path / 'ckpt')
        overrides = ('{"max_seq_len":32,"vocab_size":128,"dim":32,'
                     '"n_layers":2,"n_heads":2,"n_kv_heads":1,'
                     '"ffn_dim":64}')
        run = (f"python3 -m skypilot_tpu.train --platform cpu "
               f"--model llama-tiny --steps 6 --global-batch-size 8 "
               f"--seq-len 32 --mesh data=8 "
               f"--model-overrides '{overrides}' "
               f"--checkpoint-dir {ckpt} --checkpoint-every 3 "
               f"--log-every 3 && sleep 600")
        job_id = jobs.launch(_local_task(run, name='mjt'),
                             controller_mode='thread')

        def _ckpt_done():
            try:
                from skypilot_tpu.train import checkpoint as ckpt_lib
                mgr = ckpt_lib.make_manager(ckpt)
                return (mgr.latest_step() or 0) >= 6
            except Exception:  # noqa: BLE001 — dir not created yet
                return False

        _wait(_ckpt_done, timeout=240, gap=1.0,
              desc='training reached step 6 and checkpointed')

        cluster_name = _task_row(job_id)['cluster_name']
        record = global_user_state.get_cluster_from_name(cluster_name)
        local_instance.terminate_instances(
            record['handle'].cluster_name_on_cloud)
        _wait(lambda: _task_row(job_id)['recovery_count'] >= 1,
              timeout=300, gap=0.5, desc='recovery')
        # Relaunch cost (provision + agent + jax startup) is machine-
        # load-dependent: the budget is generous AND load-scaled (a
        # green run returns as soon as the transition lands).
        _wait(lambda: _task_row(job_id)['status'] ==
              jobs.ManagedJobStatus.RUNNING, timeout=300, gap=0.5,
              desc='RUNNING after recovery')

        # The recovered incarnation restored step 6 (its log says so)
        # rather than re-training from scratch.
        def _restored_logged():
            rec2 = global_user_state.get_cluster_from_name(cluster_name)
            if rec2 is None:
                return False
            root = rec2['handle'].head_agent_root
            import glob
            import os as os_lib
            for path in glob.glob(os_lib.path.join(
                    root, '.skytpu_agent', 'job_logs', 'job_*',
                    'run.log')):
                with open(path, encoding='utf-8') as f:
                    if 'Restored checkpoint step 6' in f.read():
                        return True
            return False

        _wait(_restored_logged, timeout=240, gap=1.0,
              desc='recovered run restored step 6')
        from skypilot_tpu.train import checkpoint as ckpt_lib
        assert ckpt_lib.make_manager(ckpt).latest_step() == 6
        jobs.cancel([job_id])
        jobs.wait(job_id, timeout=120)
