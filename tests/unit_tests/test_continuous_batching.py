"""Continuous batching: slot-based decode with prefill interleaving.

The decisive test: a slot freed mid-stream (EOS/budget) is reused by a
NEW prompt while other slots keep decoding, and every request's greedy
output equals the cache-free full re-forward — proving per-slot
cursors, kv-mask isolation, and cache-row inserts never
cross-contaminate.
"""
import jax
import jax.numpy as jnp
import pytest

from skypilot_tpu.infer import engine as engine_lib
from tests.unit_tests.test_infer import _OVERRIDES, _reference_greedy


@pytest.fixture(scope='module')
def cbe():
    return engine_lib.ContinuousBatchingEngine(
        'llama-tiny', n_slots=2, model_overrides=dict(_OVERRIDES),
        param_dtype=jnp.float32, prefill_bucket=8)


class TestContinuousCorrectness:

    def test_single_request_matches_cache_free(self, cbe):
        prompt = [5, 17, 3, 42, 8]
        got = cbe.generate(
            [prompt], engine_lib.SamplingConfig(max_new_tokens=6))[0]
        want = _reference_greedy(cbe.params, prompt, 6)
        assert got == want, (got, want)

    def test_slot_reuse_mid_stream_matches_cache_free(self, cbe):
        """3 requests, 2 slots, different budgets: A finishes first, C
        is admitted into A's slot while B is mid-decode."""
        a, b, c = [5, 17, 3], [9, 1, 30, 31], [7, 8, 9, 10, 11]
        rid_a = cbe.submit(a, engine_lib.SamplingConfig(
            max_new_tokens=2))
        rid_b = cbe.submit(b, engine_lib.SamplingConfig(
            max_new_tokens=9))
        rid_c = cbe.submit(c, engine_lib.SamplingConfig(
            max_new_tokens=4))
        # Drive manually and observe the interleaving: C must enter
        # while B is still active.
        steps_when_c_admitted = None
        n = 0
        while any(not cbe._events[r].is_set()
                  for r in (rid_a, rid_b, rid_c)):
            assert cbe.step()
            n += 1
            if steps_when_c_admitted is None and any(
                    s is not None and s.request_id == rid_c
                    for s in cbe._slots):
                steps_when_c_admitted = n
                assert any(s is not None and s.request_id == rid_b
                           for s in cbe._slots), \
                    'C should share the batch with a live B'
            assert n < 50
        assert steps_when_c_admitted is not None
        assert cbe.wait(rid_a) == _reference_greedy(cbe.params, a, 2)
        assert cbe.wait(rid_b) == _reference_greedy(cbe.params, b, 9)
        assert cbe.wait(rid_c) == _reference_greedy(cbe.params, c, 4)

    def test_queueing_beyond_slots(self, cbe):
        """More prompts than slots: generate() drains the queue."""
        prompts = [[5, 17, 3], [9, 1], [30, 31, 32], [4, 4, 4, 4],
                   [50, 60]]
        outs = cbe.generate(
            prompts, engine_lib.SamplingConfig(max_new_tokens=3))
        for p, got in zip(prompts, outs):
            assert got == _reference_greedy(cbe.params, p, 3), p

    def test_eos_evicts_slot(self, cbe):
        prompt = [5, 17, 3]
        base = cbe.generate(
            [prompt], engine_lib.SamplingConfig(max_new_tokens=8))[0]
        eos = base[2]
        got = cbe.generate(
            [prompt],
            engine_lib.SamplingConfig(max_new_tokens=8, eos_id=eos))[0]
        assert got == base[:3], (got, base)

    def test_mixed_greedy_and_sampled_rows(self, cbe):
        """Greedy and temperature>0 requests share one decode step;
        the greedy row stays exact."""
        g, s = [5, 17, 3, 42, 8], [1, 2, 3]
        rid_g = cbe.submit(g, engine_lib.SamplingConfig(
            max_new_tokens=5))
        rid_s = cbe.submit(s, engine_lib.SamplingConfig(
            max_new_tokens=5, temperature=1.0))
        cbe.run_until_idle()
        assert cbe.wait(rid_g) == _reference_greedy(cbe.params, g, 5)
        sampled = cbe.wait(rid_s)
        assert len(sampled) == 5
        assert all(0 <= t < cbe.config.vocab_size for t in sampled)

    def test_mixed_sampling_interleaves_in_one_batch(self, cbe):
        """top_k/top_p are per-row traced vectors (round-4): a greedy
        and a top-k request DECODE TOGETHER — no drain wait, no
        head-of-line stall — and each still produces exactly what it
        produces alone (the request-level/solo reference)."""
        g1, g2 = [5, 17, 3], [9, 1, 30]
        topk_cfg = engine_lib.SamplingConfig(
            max_new_tokens=6, temperature=1.0, top_k=5, seed=77)
        solo_topk = cbe.generate([g2], topk_cfg)[0]
        rid_plain = cbe.submit(g1, engine_lib.SamplingConfig(
            max_new_tokens=6))
        rid_topk = cbe.submit(g2, topk_cfg)
        cbe.step()
        # Both live in the SAME decode batch despite different pairs.
        live_pairs = {(s.top_k, s.top_p) for s in cbe._slots
                      if s is not None}
        assert live_pairs == {(0, 1.0), (5, 1.0)}
        cbe.run_until_idle()
        assert cbe.wait(rid_plain) == _reference_greedy(
            cbe.params, g1, 6)
        assert cbe.wait(rid_topk) == solo_topk

    def test_mixed_top_p_and_top_k_match_solo(self, cbe):
        """A top-p row and a top-k row sharing the batch each match
        their solo output (per-row cutoffs don't cross-contaminate)."""
        p1, p2 = [5, 17, 3, 42], [9, 1]
        topp_cfg = engine_lib.SamplingConfig(
            max_new_tokens=5, temperature=1.0, top_p=0.7, seed=11)
        topk_cfg = engine_lib.SamplingConfig(
            max_new_tokens=5, temperature=1.0, top_k=3, seed=22)
        solo_p = cbe.generate([p1], topp_cfg)[0]
        solo_k = cbe.generate([p2], topk_cfg)[0]
        rid_p = cbe.submit(p1, topp_cfg)
        rid_k = cbe.submit(p2, topk_cfg)
        cbe.run_until_idle()
        assert cbe.wait(rid_p) == solo_p
        assert cbe.wait(rid_k) == solo_k

    def test_top_k_bucket_bounds_compile_cache(self):
        bucket = engine_lib.top_k_bucket
        assert bucket(0, 96) == 0
        assert bucket(1, 96) == 1
        assert bucket(5, 96) == 8
        assert bucket(8, 96) == 8
        assert bucket(70, 96) == 96      # capped at vocab
        # Distinct user ks collapse onto few buckets.
        assert {bucket(k, 4096) for k in range(1, 100)} == \
            {1, 2, 4, 8, 16, 32, 64, 128}

    def test_cancel_releases_bookkeeping(self, cbe):
        """Canceled requests (queued, active, or finished-unread) leave
        no events/results behind."""
        base_events = len(cbe._events)
        # Queued cancel.
        rid_q = cbe.submit([1, 2], engine_lib.SamplingConfig(
            max_new_tokens=4))
        cbe.cancel(rid_q)
        assert rid_q not in cbe._events and not cbe._queue
        # Active cancel: admit, then cancel mid-decode.
        rid_a = cbe.submit([1, 2, 3], engine_lib.SamplingConfig(
            max_new_tokens=8))
        assert cbe.step()
        cbe.cancel(rid_a)
        cbe.run_until_idle()
        assert rid_a not in cbe._results and rid_a not in cbe._events
        assert all(s is None for s in cbe._slots)
        # Finished-unread cancel.
        rid_f = cbe.submit([4, 5], engine_lib.SamplingConfig(
            max_new_tokens=2))
        cbe.run_until_idle()
        assert rid_f in cbe._results
        cbe.cancel(rid_f)
        assert rid_f not in cbe._results and rid_f not in cbe._events
        assert len(cbe._events) == base_events

    def test_overlong_request_rejected(self, cbe):
        with pytest.raises(ValueError, match='max_seq_len'):
            cbe.submit(list(range(60)),
                       engine_lib.SamplingConfig(max_new_tokens=30))


class TestChunkedPrefill:

    @pytest.fixture(scope='class')
    def cpe(self):
        return engine_lib.ContinuousBatchingEngine(
            'llama-tiny', n_slots=2, model_overrides=dict(_OVERRIDES),
            param_dtype=jnp.float32, prefill_bucket=4,
            prefill_chunk=4)

    def test_chunked_matches_cache_free(self, cpe):
        prompt = list(range(3, 17))  # 14 tokens -> 4 chunks of <=4
        got = cpe.generate(
            [prompt], engine_lib.SamplingConfig(max_new_tokens=5))[0]
        assert got == _reference_greedy(cpe.params, prompt, 5)

    def test_decode_interleaves_between_chunks(self, cpe):
        """While a long prompt prefills chunk-by-chunk, a live slot
        keeps generating."""
        short, long_p = [5, 17, 3], list(range(1, 20))  # 19 -> 5 chunks
        rid_s = cpe.submit(short, engine_lib.SamplingConfig(
            max_new_tokens=12))
        assert cpe.step()  # admit+prefill short (fits one tick)
        rid_l = cpe.submit(long_p, engine_lib.SamplingConfig(
            max_new_tokens=3))
        progressed_during_prefill = []
        while any(p.rid == rid_l for p in cpe._prefills) or \
                not any(s is not None and s.request_id == rid_l
                        for s in cpe._slots):
            short_slot = next((s for s in cpe._slots
                               if s is not None
                               and s.request_id == rid_s), None)
            if short_slot is None:
                break  # short finished before long admitted
            progressed_during_prefill.append(short_slot.generated)
            if not cpe.step():
                break
        # The short request generated tokens across the long one's
        # prefill ticks.
        assert len(set(progressed_during_prefill)) > 1, \
            progressed_during_prefill
        cpe.run_until_idle()
        assert cpe.wait(rid_s) == _reference_greedy(cpe.params, short,
                                                    12)
        assert cpe.wait(rid_l) == _reference_greedy(cpe.params, long_p,
                                                    3)

    def test_concurrent_long_prompts_prefill_round_robin(self):
        """Round-4 (verdict weak #7): several long prompts advance one
        chunk EACH per tick — the second must not wait for the first's
        whole chunk sequence — and both decode correctly."""
        eng = engine_lib.ContinuousBatchingEngine(
            'llama-tiny', n_slots=2, model_overrides=dict(_OVERRIDES),
            param_dtype=jnp.float32, prefill_bucket=4,
            prefill_chunk=4)
        long_a = list(range(1, 18))   # 17 tokens -> 5 chunks of 4
        long_b = list(range(20, 37))  # 17 tokens -> 5 chunks
        rid_a = eng.submit(long_a, engine_lib.SamplingConfig(
            max_new_tokens=3))
        rid_b = eng.submit(long_b, engine_lib.SamplingConfig(
            max_new_tokens=3))
        eng.step()  # both admitted into reserved slots
        assert len(eng._prefills) == 2
        done_before = [p.done for p in eng._prefills]
        eng.step()
        done_after = {p.rid: p.done for p in eng._prefills}
        # BOTH pending prefills advanced on the same tick.
        assert done_after[rid_a] > done_before[0]
        assert done_after[rid_b] > done_before[1]
        eng.run_until_idle()
        assert eng.wait(rid_a) == _reference_greedy(eng.params,
                                                    long_a, 3)
        assert eng.wait(rid_b) == _reference_greedy(eng.params,
                                                    long_b, 3)

    def test_size_one_chunks_stay_on_prefill_path(self):
        """chunk=1 makes every prefill forward s==1 — it must trace
        the global-cursor prefill branch, NOT slot-mode (which would
        scatter each prompt token's K/V at the row's last revealed
        slot and silently corrupt generation)."""
        eng = engine_lib.ContinuousBatchingEngine(
            'llama-tiny', n_slots=2, model_overrides=dict(_OVERRIDES),
            param_dtype=jnp.float32, prefill_bucket=4,
            prefill_chunk=1)
        prompt = [5, 17, 3, 42, 8, 9, 1]
        got = eng.generate(
            [prompt], engine_lib.SamplingConfig(max_new_tokens=5))[0]
        assert got == _reference_greedy(eng.params, prompt, 5)

    def test_padding_chunks_are_skipped(self):
        """A short prompt in a large bucket must not burn ticks
        prefilling pure padding."""
        eng = engine_lib.ContinuousBatchingEngine(
            'llama-tiny', n_slots=1, model_overrides=dict(_OVERRIDES),
            param_dtype=jnp.float32, prefill_bucket=32,
            prefill_chunk=4)
        rid = eng.submit([5, 17, 3], engine_lib.SamplingConfig(
            max_new_tokens=2))
        ticks = 0
        while any(p.rid == rid for p in eng._prefills) or not any(
                s is not None and s.request_id == rid
                for s in eng._slots):
            assert eng.step()
            ticks += 1
            assert ticks < 4  # 1 chunk covers the 3-token prompt
        eng.run_until_idle()
        assert eng.wait(rid) == _reference_greedy(
            eng.params, [5, 17, 3], 2)

    def test_cancel_mid_chunked_prefill(self, cpe):
        long_p = list(range(1, 20))
        rid = cpe.submit(long_p, engine_lib.SamplingConfig(
            max_new_tokens=3))
        cpe.step()  # first chunk
        assert any(p.rid == rid for p in cpe._prefills)
        cpe.cancel(rid)
        cpe.run_until_idle()
        assert not cpe._prefills
        assert rid not in cpe._results and rid not in cpe._events
        assert all(s is None for s in cpe._slots)


class TestKvReadBucket:

    def test_bucketed_reads_match_cache_free(self):
        """Decode with a tiny read bucket (8) must cross several
        bucket boundaries mid-generation and stay exact."""
        eng = engine_lib.ContinuousBatchingEngine(
            'llama-tiny', n_slots=2, model_overrides=dict(_OVERRIDES),
            param_dtype=jnp.float32, prefill_bucket=8,
            kv_read_bucket=8)
        a, b = [5, 17, 3, 42, 8, 9], [7, 7]
        outs = eng.generate(
            [a, b], engine_lib.SamplingConfig(max_new_tokens=20))
        assert outs[0] == _reference_greedy(eng.params, a, 20)
        assert outs[1] == _reference_greedy(eng.params, b, 20)

    def test_bucket_never_below_deepest_cursor(self):
        eng = engine_lib.ContinuousBatchingEngine(
            'llama-tiny', n_slots=2, model_overrides=dict(_OVERRIDES),
            param_dtype=jnp.float32, prefill_bucket=8,
            kv_read_bucket=8)
        # Slot A deep in context, slot B fresh: the shared bucket must
        # cover A, and B must still be exact.
        rid_a = eng.submit(list(range(1, 12)),
                           engine_lib.SamplingConfig(max_new_tokens=16))
        for _ in range(10):
            eng.step()
        rid_b = eng.submit([4, 5], engine_lib.SamplingConfig(
            max_new_tokens=4))
        eng.run_until_idle()
        assert eng.wait(rid_a) == _reference_greedy(
            eng.params, list(range(1, 12)), 16)
        assert eng.wait(rid_b) == _reference_greedy(
            eng.params, [4, 5], 4)


class TestContinuousServer:

    def test_concurrent_requests_share_decode_batch(self):
        """Concurrent /generate requests through the continuous server
        all return the cache-free-correct greedy outputs."""
        import concurrent.futures
        import json
        import urllib.request

        from skypilot_tpu.infer import server as server_lib
        srv = server_lib.InferenceServer(allow_random_weights=True, 
            model='llama-tiny', port=0, host='127.0.0.1',
            max_batch_size=2, model_overrides=dict(_OVERRIDES))
        assert srv.continuous
        srv.start()
        import threading
        threading.Thread(target=lambda s=srv._server: s.serve_forever(poll_interval=0.05),  # pylint: disable=protected-access
                         daemon=True).start()
        prompts = [[5, 17, 3], [9, 1], [30, 31, 32], [4, 4, 4, 4]]

        def _post(p):
            req = urllib.request.Request(
                f'http://127.0.0.1:{srv.port}/generate',
                data=json.dumps({'prompt_ids': [p],
                                 'max_new_tokens': 4}).encode(),
                headers={'Content-Type': 'application/json'})
            with urllib.request.urlopen(req, timeout=120) as r:
                return json.load(r)['tokens'][0]
        try:
            with concurrent.futures.ThreadPoolExecutor(4) as pool:
                got = list(pool.map(_post, prompts))
            for p, tokens in zip(prompts, got):
                assert tokens == _reference_greedy(
                    srv.engine.params, p, 4), p
        finally:
            srv.shutdown()


class TestPerRequestSeeds:

    @pytest.fixture(scope='class')
    def seng(self):
        return engine_lib.ContinuousBatchingEngine(
            'llama-tiny', n_slots=2, model_overrides=dict(_OVERRIDES),
            param_dtype=jnp.float32, prefill_bucket=8)

    def test_seeded_request_reproducible_across_batches(self, seng):
        """Same (prompt, seed) twice — once alone, once sharing the
        batch with other traffic — must produce identical tokens."""
        cfg = engine_lib.SamplingConfig(max_new_tokens=6,
                                        temperature=1.0, seed=1234)
        alone = seng.generate([[5, 17, 3]], cfg)[0]
        rid_noise = seng.submit([9, 1, 30], engine_lib.SamplingConfig(
            max_new_tokens=10, temperature=1.0))
        seng.step()
        rid_seeded = seng.submit([5, 17, 3], cfg)
        seng.run_until_idle()
        assert seng.wait(rid_seeded) == alone
        seng.wait(rid_noise)

    def test_different_seeds_differ(self, seng):
        cfg1 = engine_lib.SamplingConfig(max_new_tokens=8,
                                         temperature=1.0, seed=1)
        cfg2 = engine_lib.SamplingConfig(max_new_tokens=8,
                                         temperature=1.0, seed=2)
        a = seng.generate([[5, 17, 3]], cfg1)[0]
        b = seng.generate([[5, 17, 3]], cfg2)[0]
        assert a != b

    def test_greedy_ignores_seed(self, seng):
        a = seng.generate([[5, 17, 3]], engine_lib.SamplingConfig(
            max_new_tokens=4, seed=7))[0]
        assert a == _reference_greedy(seng.params, [5, 17, 3], 4)

    def test_bad_seed_rejected_at_submit(self, seng):
        with pytest.raises(ValueError, match='seed'):
            seng.submit([1, 2], engine_lib.SamplingConfig(
                max_new_tokens=4, seed='not-a-number'))
        # Out-of-int32 seeds are masked, not fatal.
        out = seng.generate([[1, 2]], engine_lib.SamplingConfig(
            max_new_tokens=2, temperature=1.0, seed=2**40))[0]
        assert len(out) == 2

    def test_request_level_engine_seeds_the_call(self):
        eng = engine_lib.InferenceEngine(
            'llama-tiny', max_batch_size=2,
            model_overrides=dict(_OVERRIDES),
            param_dtype=jnp.float32)
        cfg = engine_lib.SamplingConfig(max_new_tokens=6,
                                        temperature=1.0, seed=99)
        a = eng.generate([[5, 17, 3]], cfg)[0]
        b = eng.generate([[5, 17, 3]], cfg)[0]
        assert a == b  # call-level reproducibility


class TestTimeoutCleanup:
    """wait()/stream() timeouts must leave the engine exactly as a
    cancel() would: no _events/_results/_stream_queues entries for the
    abandoned request, and its decode slot freed — a client that gives
    up must not leak bookkeeping (or a slot) in a long-lived replica."""

    def test_wait_timeout_releases_queued_request(self, cbe):
        base_events = len(cbe._events)
        rid = cbe.submit([1, 2], engine_lib.SamplingConfig(
            max_new_tokens=4))
        with pytest.raises(TimeoutError):
            cbe.wait(rid, timeout=0.05)  # nothing drives step()
        assert rid not in cbe._events
        assert rid not in cbe._results
        assert not cbe._queue
        assert len(cbe._events) == base_events

    def test_wait_timeout_frees_active_slot(self, cbe):
        rid = cbe.submit([1, 2, 3], engine_lib.SamplingConfig(
            max_new_tokens=8))
        assert cbe.step()  # admitted into a slot
        assert any(s is not None and s.request_id == rid
                   for s in cbe._slots)
        with pytest.raises(TimeoutError):
            cbe.wait(rid, timeout=0.05)
        cbe.run_until_idle()  # step() evicts the canceled request
        assert rid not in cbe._events
        assert rid not in cbe._results
        assert all(s is None for s in cbe._slots)

    def test_stream_timeout_releases_bookkeeping(self, cbe):
        base_events = len(cbe._events)
        rid = cbe.submit([5, 17, 3], engine_lib.SamplingConfig(
            max_new_tokens=8), stream=True)
        assert cbe.step()  # admit; a first token may already be queued
        it = cbe.stream(rid, timeout=0.05)
        with pytest.raises(TimeoutError):
            for _ in it:  # drains queued tokens, then stalls
                pass
        cbe.run_until_idle()
        assert rid not in cbe._events
        assert rid not in cbe._results
        assert rid not in cbe._stream_queues
        assert all(s is None for s in cbe._slots)
        assert len(cbe._events) == base_events


class TestTopPSortSkip:
    """When every nucleus row also ran top-k (`top_p_in_topk`), the
    top-p cutoff reads the descending lax.top_k window instead of a
    full-vocab sort.  The promise: rows with top_ps < 1.0 have
    top_ks > 0; rows with top_ks <= 0 must carry top_ps >= 1.0."""

    def _rows(self, top_p_in_topk):
        key = jax.random.PRNGKey(3)
        logits = jax.random.normal(key, (4, 96)) * 3.0
        keys = jax.random.split(jax.random.PRNGKey(7), 4)
        temps = jnp.ones((4,), jnp.float32)
        # Row 2 is the keep-all edge: no top-k, top_p == 1.0.
        top_ks = jnp.asarray([3, 5, 0, 8], jnp.int32)
        top_ps = jnp.asarray([0.7, 0.9, 1.0, 0.5], jnp.float32)
        return engine_lib.sample_logits_rows(
            logits, keys, temps, top_ks, top_ps, max_k=8,
            use_top_p=True, top_p_in_topk=top_p_in_topk)

    def test_windowed_cutoff_matches_full_sort(self):
        fast = self._rows(True)
        slow = self._rows(False)
        assert fast.tolist() == slow.tolist()

    def test_topk_plus_topp_batch_matches_solo(self, cbe):
        """A top-k+top-p row (sort-skip eligible) sharing the batch
        with a plain top-k row reproduces its solo output."""
        p1, p2 = [5, 17, 3, 42], [9, 1]
        both_cfg = engine_lib.SamplingConfig(
            max_new_tokens=5, temperature=1.0, top_k=6, top_p=0.7,
            seed=31)
        topk_cfg = engine_lib.SamplingConfig(
            max_new_tokens=5, temperature=1.0, top_k=3, seed=22)
        solo = cbe.generate([p1], both_cfg)[0]
        rid_b = cbe.submit(p1, both_cfg)
        rid_k = cbe.submit(p2, topk_cfg)
        cbe.run_until_idle()
        assert cbe.wait(rid_b) == solo
        cbe.wait(rid_k)
