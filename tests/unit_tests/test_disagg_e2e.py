"""Disaggregated prefill/decode serving end-to-end: a prefill-role
replica and a decode-role replica behind the router must be
indistinguishable — byte-for-byte on the greedy token stream — from a
single ``--role both`` replica, across model families, KV-cache modes,
and both speculation flavors.

The fleet is real: in-process ``InferenceServer`` replicas (one
started with ``role='prefill'``, one with ``role='decode'``) behind a
hand-ticked ``Router`` that learns the roles from /health?verbose=1
and stamps the decode target header on every request it forwards to
the prefill replica.  The prefill replica runs the chunked prefill,
samples the seed token, ships the KV artifact to the decode replica
over POST /handoff, and relays the decode replica's token stream back
— the client sees one ordinary response.

Also here: supervisor pool mechanics (per-role spawn/respawn, pools
scaling independently on their own signals, per-pool drain victims)
over stub process handles, and the HTTP rejection arms for hostile or
version-skewed artifacts.

Tier-1/CPU by design: everything in this file runs under
`JAX_PLATFORMS=cpu -m 'not slow'` (TestTier1Guard enforces it for
every test surface this PR added).
"""
import json
import threading
import time
import urllib.error
import urllib.request
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import pytest

from skypilot_tpu.infer import handoff as handoff_lib
from skypilot_tpu.infer.server import InferenceServer
from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.serve import replica_supervisor as sup_lib
from skypilot_tpu.serve.router import Router

_COMMON = {'max_seq_len': 64, 'n_layers': 2,
           'dtype': jnp.float32, 'param_dtype': jnp.float32}
_FAMILIES = {
    # GQA 4:2 + rope.
    'llama-tiny': {**_COMMON, 'n_heads': 4, 'n_kv_heads': 2,
                   'dim': 64, 'ffn_dim': 128, 'vocab_size': 96},
    # MHA + learned positions (no rope): the handoff's cache-cursor
    # contract must hold without rope interpolation too.
    'gpt2-tiny': {**_COMMON, 'n_heads': 4, 'dim': 64,
                  'ffn_dim': 128, 'vocab_size': 96},
}
_PS = 8
# Repetitive prompts so n-gram self-drafting actually proposes.
_PROMPTS = [[5, 17, 3, 42, 5, 17, 3, 9, 5, 17, 3], [9, 1, 4, 9, 1, 4]]
_MAX_NEW = 8

# families x cache modes x speculation: each mode builds a reference
# `--role both` server plus a prefill+decode fleet from the same kwargs.
_MODES = {
    'llama-paged': dict(model='llama-tiny', page_size=_PS,
                        prefill_chunk=_PS),
    'llama-paged-int8-ngram': dict(model='llama-tiny', page_size=_PS,
                                   kv_cache_dtype='int8', spec_k=4),
    'gpt2-contig-draft': dict(model='gpt2-tiny', spec_k=4,
                              draft_model='gpt2-tiny'),
}


def _server(model, role='both', **kw):
    reg = metrics_lib.Registry()  # one registry per replica
    overrides = dict(_FAMILIES[model])
    if kw.get('draft_model'):
        kw.setdefault('draft_overrides', dict(overrides))
    srv = InferenceServer(model=model, port=0, host='127.0.0.1',
                          max_batch_size=2,
                          model_overrides=overrides,
                          allow_random_weights=True, registry=reg,
                          role=role, **kw)
    srv.start()
    threading.Thread(
        target=lambda s=srv._server: s.serve_forever(poll_interval=0.05),
        daemon=True).start()
    return srv, reg


@pytest.fixture(scope='module', params=sorted(_MODES))
def fleet(request):
    kw = dict(_MODES[request.param])
    model = kw.pop('model')
    ref, ref_reg = _server(model, **kw)
    pre, pre_reg = _server(model, role='prefill', **kw)
    dec, dec_reg = _server(model, role='decode', **kw)
    registry = metrics_lib.Registry()
    router = Router(
        replicas=[f'http://127.0.0.1:{pre.port}',
                  f'http://127.0.0.1:{dec.port}'],
        registry=registry, health_interval_s=3600.0,  # hand-ticked
        health_timeout_s=5.0, attempt_timeout_s=60.0,
        request_budget_s=60.0)
    router.start()
    # Settle: both replicas routable AND the router has learned both
    # roles from /health?verbose=1 (routing depends on them).
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        router.health_tick()
        views = router.views()
        if (len(views) == 2 and all(v.routable for v in views)
                and {v.role for v in views} == {'prefill', 'decode'}):
            break
        time.sleep(0.05)
    else:
        raise AssertionError(
            f'fleet never settled: '
            f'{[v.snapshot() for v in router.views()]}')
    fl = SimpleNamespace(mode=request.param, kw=kw, router=router,
                         ref=ref, pre=pre, dec=dec, ref_reg=ref_reg,
                         pre_reg=pre_reg, dec_reg=dec_reg)
    yield fl
    router.stop()
    for srv in (ref, pre, dec):
        srv.shutdown()


def _post_json(base, path, body, timeout=60):
    req = urllib.request.Request(
        base + path, data=json.dumps(body).encode(), method='POST',
        headers={'Content-Type': 'application/json'})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        with e:
            return e.code, dict(e.headers), e.read()


def _generate(base, prompts, max_new=_MAX_NEW):
    code, headers, body = _post_json(
        base, '/generate',
        {'prompt_ids': prompts, 'max_new_tokens': max_new,
         'temperature': 0.0})
    assert code == 200, body
    return json.loads(body)['tokens'], headers


def _sse_stream(base, prompt_text, max_new=_MAX_NEW, timeout=60):
    """(ordered text fragments, finish_reason) from a completions SSE
    stream — the byte-level payload minus per-server response ids."""
    req = urllib.request.Request(
        base + '/v1/completions',
        data=json.dumps({'model': 'fleet-model', 'prompt': prompt_text,
                         'max_tokens': max_new, 'temperature': 0.0,
                         'stream': True}).encode(),
        method='POST', headers={'Content-Type': 'application/json'})
    fragments, finish = [], None
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        assert resp.headers['Content-Type'].startswith(
            'text/event-stream')
        for raw in resp:
            line = raw.decode().strip()
            if not line.startswith('data: '):
                continue
            payload = line[len('data: '):]
            if payload == '[DONE]':
                break
            obj = json.loads(payload)
            assert 'error' not in obj, obj
            choice = obj['choices'][0]
            text = choice.get('text') or ''
            if text:
                fragments.append(text)
            if choice.get('finish_reason'):
                finish = choice['finish_reason']
    return fragments, finish


def _counter(reg, name, **labels):
    parsed = metrics_lib.parse_exposition(reg.expose())
    return metrics_lib.sample_value(parsed, name, **labels) or 0.0


class TestDisaggFleet:

    def test_roles_learned_and_decode_shielded(self, fleet):
        """The router learned both roles and never selects the decode
        replica for client traffic — it is reachable through the
        handoff path only."""
        by_role = {v.role: v for v in fleet.router.views()}
        assert set(by_role) == {'prefill', 'decode'}
        assert by_role['prefill'].url.endswith(str(fleet.pre.port))
        for key in (None, 1, 2, 3):
            picked = fleet.router.select_replica(key)
            assert picked is not None and picked.role == 'prefill'
        target = fleet.router._select_decode_target(1)
        assert target is not None and target.role == 'decode'

    def test_greedy_tokens_byte_identical_through_handoff(self, fleet):
        """The tentpole parity pin: token ids through router ->
        prefill -> handoff -> decode equal a single `--role both`
        replica's, and the handoff counters prove the path was the
        disaggregated one."""
        export0 = _counter(fleet.pre_reg,
                           'skytpu_handoff_requests_total',
                           side='export')
        admit0 = _counter(fleet.dec_reg,
                          'skytpu_handoff_requests_total',
                          side='admit')
        want, _ = _generate(f'http://127.0.0.1:{fleet.ref.port}',
                            _PROMPTS)
        got, headers = _generate(fleet.router.url, _PROMPTS)
        assert got == want, (fleet.mode, got, want)
        # The router delivered to the prefill replica...
        assert headers['X-Served-By'].endswith(str(fleet.pre.port))
        # ...which exported one artifact per prompt; the decode
        # replica admitted every one of them.  (Deltas, not lifetime
        # totals: the prefill replica's startup warmup generate()
        # exports and self-drains one artifact that never ships.)
        assert _counter(fleet.pre_reg, 'skytpu_handoff_requests_total',
                        side='export') - export0 == len(_PROMPTS)
        assert _counter(fleet.dec_reg, 'skytpu_handoff_requests_total',
                        side='admit') - admit0 == len(_PROMPTS)

    def test_sse_stream_byte_identical_through_handoff(self, fleet):
        """Streaming path: the relayed ndjson token stream re-emerges
        as an SSE stream whose text fragments match the reference
        replica's fragment-for-fragment."""
        prompt = 'sky sky sky sky'
        want = _sse_stream(f'http://127.0.0.1:{fleet.ref.port}', prompt)
        got = _sse_stream(fleet.router.url, prompt)
        assert got == want, (fleet.mode, got, want)

    def test_prefix_dedupe_across_the_wire(self, fleet):
        """A repeated prompt's second handoff ships only the tail: the
        decode replica already holds the prefix pages via its
        chain-hash map and admits them by page id."""
        if not fleet.kw.get('page_size'):
            pytest.skip('dedupe is a paged-allocator property')
        prompt = [(7 + i) % 90 for i in range(19)]  # 3 pages at ps=8
        base = _counter(fleet.dec_reg, 'skytpu_handoff_pages_total',
                        kind='deduped')
        _generate(fleet.router.url, [prompt])
        _generate(fleet.router.url, [prompt])
        shipped = _counter(fleet.dec_reg, 'skytpu_handoff_pages_total',
                           kind='shipped')
        deduped = _counter(fleet.dec_reg, 'skytpu_handoff_pages_total',
                           kind='deduped')
        assert shipped >= 1
        assert deduped >= base + 2, (base, shipped, deduped)

    def test_handoff_rejections_over_http(self, fleet):
        """Hostile/skewed artifacts die at the door: 400 for garbage,
        409 for a version the receiver does not speak."""
        dec = f'http://127.0.0.1:{fleet.dec.port}'

        def _post_blob(blob):
            req = urllib.request.Request(
                dec + '/handoff', data=blob, method='POST',
                headers={'Content-Type': 'application/octet-stream'})
            try:
                with urllib.request.urlopen(req, timeout=30) as resp:
                    return resp.status
            except urllib.error.HTTPError as e:
                with e:
                    return e.code
        assert _post_blob(b'garbage, not a handoff artifact') == 400
        skewed = handoff_lib._PREAMBLE.pack(
            handoff_lib.MAGIC, handoff_lib.VERSION + 1, 0)
        assert _post_blob(skewed) == 409

    def test_both_sides_leak_free(self, fleet):
        """After all of the handoff traffic above, both allocators are
        clean and each replica reports its role in verbose health."""
        for srv, role in ((fleet.pre, 'prefill'), (fleet.dec, 'decode'),
                          (fleet.ref, 'both')):
            with urllib.request.urlopen(
                    f'http://127.0.0.1:{srv.port}/health?verbose=1',
                    timeout=10) as resp:
                detail = json.loads(resp.read())
            assert detail['status'] == 'ok'
            assert detail['role'] == role
            assert detail['leak_report'] is None, (role, detail)


# ---------------------------------------------------------------------
# Supervisor pools (stub handles; replica processes are not the point)
# ---------------------------------------------------------------------

class _NullHandle:
    """Inert Popen surface: alive until told otherwise."""

    def __init__(self):
        self._forced = None

    def poll(self):
        return self._forced

    def kill(self):
        self._forced = -9

    def terminate(self):
        self._forced = -15


class _PoolHarness:

    def __init__(self, pools, **sup_kw):
        self.calls = []
        self.registry = metrics_lib.Registry()
        self.router = Router(registry=self.registry,
                             health_interval_s=3600.0)
        self.sup = sup_lib.ReplicaSupervisor(
            self._factory, self.router, pools=pools, tick_s=3600.0,
            restart_base_delay_s=0.0, restart_max_delay_s=0.0,
            drain_timeout_s=0.05, registry=self.registry, **sup_kw)

    def _factory(self, slot_id, role):
        self.calls.append((slot_id, role))
        handle = _NullHandle()
        # Unroutable port: drain POSTs fail fast and fall through to
        # the drain deadline, which is all these tests need.
        return handle, f'http://127.0.0.1:1/{slot_id}'

    def view_for(self, slot, **fields):
        view = next(v for v in self.router.views()
                    if v.url == slot.url)
        for k, v in fields.items():
            setattr(view, k, v)
        return view


class TestSupervisorPools:

    def test_pools_spawn_role_slots_and_factory_signature(self):
        h = _PoolHarness({'prefill': {'min_replicas': 1},
                          'decode': {'min_replicas': 2}})
        h.sup.tick()
        assert sorted(role for _, role in h.calls) == \
            ['decode', 'decode', 'prefill']
        assert h.sup.min_replicas == 3
        assert sorted(s.role for s in h.sup.slots()) == \
            ['decode', 'decode', 'prefill']

    def test_pools_scale_on_their_own_signals(self):
        """Decode-pool page starvation adds a decode replica and ONLY
        a decode replica; the prefill pool holds."""
        h = _PoolHarness({
            'prefill': {'min_replicas': 1},
            'decode': {'min_replicas': 1,
                       'autoscaler': sup_lib.EngineSignalsAutoscaler(
                           min_replicas=1, signal='pages',
                           upscale_patience=1)}})
        h.sup.tick()
        decode_slot = next(s for s in h.sup.slots()
                           if s.role == 'decode')
        h.view_for(decode_slot, role='decode', health='ok',
                   queue_depth=1.0, free_pages=0.0)
        h.sup.tick()   # autoscale: creates the pending decode slot
        # Starvation over; the next tick spawns the pending slot
        # (tick order: spawn before autoscale) without growing again.
        h.view_for(decode_slot, free_pages=64.0)
        h.sup.tick()
        assert [role for _, role in h.calls].count('decode') == 2
        assert [role for _, role in h.calls].count('prefill') == 1
        assert h.sup.desired == 3

    def test_pool_scale_down_drains_own_pool_only(self):
        scaler = sup_lib.EngineSignalsAutoscaler(
            min_replicas=1, signal='pages', downscale_patience=1)
        h = _PoolHarness({'prefill': {'min_replicas': 1},
                          'decode': {'min_replicas': 1,
                                     'autoscaler': scaler}})
        h.sup.tick()
        # Grow the decode pool to 2 by hand, then let an idle pool
        # shrink it: the victim must be the NEWEST decode slot.
        h.sup._new_slot('decode')
        h.sup.tick()
        for slot in (s for s in h.sup.slots() if s.role == 'decode'):
            h.view_for(slot, role='decode', health='ok',
                       queue_depth=0.0, free_pages=64.0)
        h.sup.tick()
        draining = [s for s in h.sup.slots()
                    if s.state == sup_lib.DRAINING]
        assert [s.role for s in draining] == ['decode']
        assert draining[0].slot_id == max(
            s.slot_id for s in h.sup.slots() if s.role == 'decode')
        assert all(s.state == sup_lib.LIVE for s in h.sup.slots()
                   if s.role == 'prefill')

    def test_crashed_slot_respawns_with_its_role(self):
        h = _PoolHarness({'prefill': {'min_replicas': 1},
                          'decode': {'min_replicas': 1}})
        h.sup.tick()
        victim = next(s for s in h.sup.slots() if s.role == 'decode')
        victim.handle._forced = -9   # crash
        h.sup.tick()                 # reap -> backoff(0 delay)
        h.sup.tick()                 # respawn
        assert h.calls[-1][1] == 'decode'
        assert victim.state == sup_lib.LIVE and \
            victim.role == 'decode'

    def test_pool_validation(self):
        with pytest.raises(ValueError, match='unknown pool role'):
            _PoolHarness({'verifier': {'min_replicas': 1}})
        with pytest.raises(ValueError, match="signal"):
            sup_lib.EngineSignalsAutoscaler(signal='entropy')


# Test surfaces this PR added: scanned by the tier-1 guard below.
_PR_TEST_SURFACES = {
    'test_disagg_e2e.py': None,          # whole file
    'test_handoff.py': None,             # whole file
}


class TestTier1Guard:
    """The disaggregated e2e fleet test and the handoff unit tests run
    in the tier-1 lane: CPU backend, no `slow` marker, no TPU gating —
    the byte-identical-stream guarantee is only a guarantee if CI
    executes it on every PR."""

    def test_runs_on_cpu_backend(self):
        assert jax.default_backend() == 'cpu'

    def test_new_tests_not_slow_marked(self):
        import pathlib
        here = pathlib.Path(__file__).parent
        for fname, surfaces in _PR_TEST_SURFACES.items():
            text = (here / fname).read_text()
            if surfaces is None:
                scopes = [text]
            else:
                scopes = []
                for name in surfaces:
                    assert name in text, (fname, name)
                    scopes.append(text[text.index(name):])
            slow, tpu = 'mark.' + 'slow', 'requires' + '_tpu'
            for scope in scopes:
                assert slow not in scope, fname
                assert tpu not in scope, fname
