"""FluidStack tests: api-key auth, instance lifecycle over a mocked
REST seam, `GPU::count` plan grammar, no-stop semantics, catalog +
optimizer integration (depth of test_lambda_cloud.py)."""
import pytest

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu import optimizer as optimizer_lib
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import task as task_lib
from skypilot_tpu.catalog import fluidstack_catalog
from skypilot_tpu.clouds import registry
from skypilot_tpu.provision import common as provision_common
from skypilot_tpu.provision.fluidstack import fluidstack_api
from skypilot_tpu.provision.fluidstack import instance as fs_instance

Resources = resources_lib.Resources


@pytest.fixture(autouse=True)
def _api_key(monkeypatch):
    monkeypatch.setenv('FLUIDSTACK_API_KEY', 'fs-test')


class TestAuth:

    def test_key_from_env(self):
        assert fluidstack_api.load_api_key() == 'fs-test'

    def test_key_from_file(self, tmp_path, monkeypatch):
        monkeypatch.delenv('FLUIDSTACK_API_KEY')
        f = tmp_path / 'api_key'
        f.write_text('fs-file\n')
        monkeypatch.setenv('FLUIDSTACK_KEY_FILE', str(f))
        assert fluidstack_api.load_api_key() == 'fs-file'

    def test_check_credentials(self, tmp_path, monkeypatch):
        fs = registry.CLOUD_REGISTRY.from_str('fluidstack')
        ok, _ = fs.check_credentials()
        assert ok
        monkeypatch.delenv('FLUIDSTACK_API_KEY')
        monkeypatch.setenv('FLUIDSTACK_KEY_FILE', str(tmp_path / 'no'))
        ok, msg = fs.check_credentials()
        assert not ok and 'API key' in msg


class FakeFluidstack:
    """In-memory instance store behind the request seam."""

    def __init__(self):
        self.instances = {}
        self.keys = []
        self.counter = 0
        self.out_of_stock = False

    def request(self, method, path, body=None):
        if path == '/instances' and method == 'GET':
            return list(self.instances.values())
        if path == '/instances' and method == 'POST':
            if self.out_of_stock:
                raise fluidstack_api.FluidstackApiError(
                    400, 'out-of-stock', 'Plan out of stock')
            self.counter += 1
            iid = f'fs-{self.counter:04d}'
            self.instances[iid] = {
                'id': iid, 'name': body['name'], 'status': 'running',
                'gpu_type': body['gpu_type'],
                'gpu_count': body['gpu_count'],
                'region': body['region'],
                'ip_address': f'93.0.0.{self.counter}',
                'private_ip': f'10.2.0.{self.counter}',
            }
            return {'id': iid}
        if method == 'DELETE' and path.startswith('/instances/'):
            self.instances.pop(path.rsplit('/', 1)[1], None)
            return {}
        if path == '/ssh_keys' and method == 'GET':
            return list(self.keys)
        if path == '/ssh_keys' and method == 'POST':
            self.keys.append(dict(body))
            return dict(body)
        raise AssertionError(f'unhandled {method} {path}')


@pytest.fixture()
def fake_fs(monkeypatch):
    fake = FakeFluidstack()
    monkeypatch.setattr(fluidstack_api, 'request', fake.request)
    monkeypatch.setattr(fs_instance.fluidstack_api, 'request',
                        fake.request)
    monkeypatch.setattr(fs_instance.time, 'sleep', lambda s: None)
    return fake


def _pconfig(count=1, **node):
    node_cfg = {'instance_type': 'H100_PCIE_80GB::2', 'zone': None}
    node_cfg.update(node)
    return provision_common.ProvisionConfig(
        provider_config={'region': 'norway_2_eu'},
        authentication_config={
            'ssh_keys': 'skytpu:ssh-ed25519 AAAA key'},
        docker_config={}, node_config=node_cfg, count=count, tags={},
        resume_stopped_nodes=False)


class TestFluidstackProvisioner:

    def test_launch_query_terminate(self, fake_fs):
        record = fs_instance.run_instances('norway_2_eu', 'c1',
                                           _pconfig(count=2))
        assert len(record.created_instance_ids) == 2
        assert record.head_instance_id == 'fs-0001'
        # Plan grammar decomposed into API fields.
        inst = fake_fs.instances['fs-0001']
        assert inst['gpu_type'] == 'H100_PCIE_80GB'
        assert inst['gpu_count'] == 2
        # Framework key registered once.
        assert len(fake_fs.keys) == 1

        info = fs_instance.get_cluster_info('norway_2_eu', 'c1',
                                            {'region': 'norway_2_eu'})
        assert info.ssh_user == 'ubuntu'
        assert info.instances['fs-0001'][0].external_ip == '93.0.0.1'

        record2 = fs_instance.run_instances('norway_2_eu', 'c1',
                                            _pconfig(count=2))
        assert record2.created_instance_ids == []

        fs_instance.terminate_instances('c1',
                                        {'region': 'norway_2_eu'})
        assert fs_instance.query_instances(
            'c1', {'region': 'norway_2_eu'}) == {}

    def test_ssh_key_reused(self, fake_fs):
        fs_instance.run_instances('norway_2_eu', 'c1', _pconfig())
        fs_instance.run_instances('norway_2_eu', 'c2', _pconfig())
        assert len(fake_fs.keys) == 1

    def test_stop_raises_not_supported(self, fake_fs):
        fs_instance.run_instances('norway_2_eu', 'c1', _pconfig())
        with pytest.raises(exceptions.NotSupportedError,
                           match='cannot be stopped'):
            fs_instance.stop_instances('c1', {'region': 'norway_2_eu'})

    def test_out_of_stock_classified(self, fake_fs):
        fake_fs.out_of_stock = True
        with pytest.raises(exceptions.ResourcesUnavailableError):
            fs_instance.run_instances('norway_2_eu', 'c9', _pconfig())

    def test_plan_grammar(self):
        assert fs_instance.parse_instance_type(
            'A100_PCIE_80GB::8') == ('A100_PCIE_80GB', 8)
        with pytest.raises(exceptions.ProvisionError, match='bad'):
            fs_instance.parse_instance_type('A100_PCIE_80GB')


class TestFluidstackCloudAndCatalog:

    def test_flat_pricing_no_spot(self):
        assert fluidstack_catalog.get_hourly_cost(
            'H100_PCIE_80GB::1', use_spot=False) == pytest.approx(2.89)
        fs = registry.CLOUD_REGISTRY.from_str('fluidstack')
        feasible = fs.get_feasible_launchable_resources(
            Resources(accelerators='H100:4'))
        assert [r.instance_type for r in feasible.resources_list] == \
            ['H100_PCIE_80GB::4']
        feasible = fs.get_feasible_launchable_resources(
            Resources(accelerators='H100:4', use_spot=True))
        assert feasible.resources_list == []

    def test_feature_model(self):
        fs = registry.CLOUD_REGISTRY.from_str('fluidstack')
        from skypilot_tpu.clouds import cloud as cloud_lib
        unsupported = fs._unsupported_features_for_resources(
            Resources(cloud='fluidstack',
                      instance_type='H100_PCIE_80GB::1'))
        assert cloud_lib.CloudImplementationFeatures.STOP in unsupported
        assert cloud_lib.CloudImplementationFeatures.HOST_CONTROLLERS \
            in unsupported

    def test_optimizer_picks_fluidstack_when_cheapest(self):
        """A100-80GB:8 on-demand: FluidStack's $11.92 undercuts
        Lambda's $14.32 and the hyperscalers."""
        global_user_state.set_enabled_clouds(
            ['aws', 'azure', 'lambda', 'fluidstack'])
        t = task_lib.Task('t', run='x')
        t.set_resources(Resources(accelerators='A100-80GB:8'))
        with dag_lib.Dag() as d:
            d.add(t)
        optimizer_lib.optimize(d, quiet=True)
        assert t.best_resources.cloud.canonical_name() == 'fluidstack'
        assert t.best_resources.instance_type == 'A100_PCIE_80GB::8'
