"""Model + trainer tests on the 8-device CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import llama
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.parallel import sharding as sharding_lib
from skypilot_tpu.train import data as data_lib
from skypilot_tpu.train import trainer as trainer_lib


class TestLlama:

    def test_forward_shape(self):
        cfg = llama.get_config('llama-tiny', remat=False)
        model = llama.Llama(cfg)
        tokens = jnp.zeros((2, 64), jnp.int32)
        variables = model.init(jax.random.PRNGKey(0), tokens)
        logits = model.apply(variables, tokens)
        assert logits.shape == (2, 64, cfg.vocab_size)
        assert logits.dtype == jnp.float32

    def test_scan_matches_loop(self):
        """nn.scan over layers must be numerically identical to the
        unrolled loop given the same params."""
        cfg_scan = llama.get_config('llama-tiny', scan_layers=True,
                                    remat=False, dtype=jnp.float32)
        cfg_loop = llama.get_config('llama-tiny', scan_layers=False,
                                    remat=False, dtype=jnp.float32)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0,
                                    cfg_scan.vocab_size)
        m_scan = llama.Llama(cfg_scan)
        vs = m_scan.init(jax.random.PRNGKey(0), tokens)
        out_scan = m_scan.apply(vs, tokens)

        # Rebuild loop params from the scanned (stacked) params.
        params = sharding_lib.unbox(vs['params'])
        loop_params = {k: v for k, v in params.items() if k != 'layers'}
        for i in range(cfg_loop.n_layers):
            loop_params[f'layer_{i}'] = jax.tree.map(
                lambda x, i=i: x[i], params['layers'])
        m_loop = llama.Llama(cfg_loop)
        out_loop = m_loop.apply({'params': loop_params}, tokens)
        np.testing.assert_allclose(out_scan, out_loop, atol=2e-5,
                                   rtol=2e-5)

    def test_causality(self):
        """Changing a future token must not affect earlier logits."""
        cfg = llama.get_config('llama-tiny', remat=False)
        model = llama.Llama(cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 64), 0,
                                    cfg.vocab_size)
        variables = model.init(jax.random.PRNGKey(0), tokens)
        out1 = model.apply(variables, tokens)
        tokens2 = tokens.at[0, 50].set((tokens[0, 50] + 1) %
                                       cfg.vocab_size)
        out2 = model.apply(variables, tokens2)
        np.testing.assert_allclose(out1[0, :50], out2[0, :50], atol=1e-5)
        assert not np.allclose(out1[0, 50:], out2[0, 50:])

    def test_num_params_analytic(self):
        cfg = llama.get_config('llama-tiny')
        model = llama.Llama(cfg)
        tokens = jnp.zeros((1, 8), jnp.int32)
        variables = model.init(jax.random.PRNGKey(0), tokens)
        actual = sum(x.size for x in jax.tree.leaves(
            sharding_lib.unbox(variables['params'])))
        assert actual == llama.num_params(cfg)


class TestCompilationCache:

    @pytest.mark.slow  # CPU tier-1 budget: full trainer/engine run
    def test_repeat_run_hits_persistent_cache(self, tmp_path):
        """--compilation-cache-dir: the SECOND fresh-interpreter run
        of the same program must reuse the first run's compiled
        executables (on TPU this is 20-40s of provision-to-first-step;
        the managed-jobs recovery path points the cache at the
        checkpoint bucket)."""
        import os
        import subprocess
        import sys
        from skypilot_tpu.agent import constants as agent_constants
        cache = tmp_path / 'cc'
        env = dict(os.environ)
        env['SKYTPU_STATE_DIR'] = str(tmp_path / 'state')
        # --platform cpu: the PJRT plugin env is pure liability here
        # (a wedged tunnel would stall the subprocess at sitecustomize
        # import until the test timeout).
        env.pop(agent_constants.PJRT_PLUGIN_ENV, None)
        overrides = ('{"max_seq_len":32,"vocab_size":128,"dim":32,'
                     '"n_layers":1,"n_heads":2,"n_kv_heads":1,'
                     '"ffn_dim":64}')
        cmd = [sys.executable, '-m', 'skypilot_tpu.train',
               '--platform', 'cpu', '--model', 'llama-tiny',
               '--steps', '1', '--global-batch-size', '8',
               '--seq-len', '32', '--mesh', 'data=8,fsdp=1',
               '--compilation-cache-dir', str(cache),
               '--model-overrides', overrides, '--log-every', '1']
        proc1 = subprocess.run(cmd, env=env, capture_output=True,
                               text=True, timeout=300)
        assert proc1.returncode == 0, proc1.stderr[-2000:]
        entries_after_first = set(os.listdir(cache))
        assert entries_after_first  # executables persisted
        proc2 = subprocess.run(cmd, env=env, capture_output=True,
                               text=True, timeout=300)
        assert proc2.returncode == 0, proc2.stderr[-2000:]
        # A fully-cached second run compiles nothing new.
        assert set(os.listdir(cache)) == entries_after_first


class TestTrainer:

    def _trainer(self, **kw):
        config = trainer_lib.TrainConfig(
            model='llama-tiny', global_batch_size=8, seq_len=64,
            total_steps=20, warmup_steps=2,
            mesh=mesh_lib.MeshConfig(data=2, fsdp=-1, tensor=2),
            model_overrides={'n_heads': 4, 'n_kv_heads': 2,
                             'max_seq_len': 64}, **kw)
        return trainer_lib.Trainer(config)

    def test_params_are_sharded(self):
        trainer = self._trainer()
        state = trainer.init_state()
        # The embedding must be sharded over tensor (vocab) and fsdp.
        embed = state.params['tok_embed']
        spec = embed.sharding.spec
        assert 'tensor' in str(spec) or 'fsdp' in str(spec), spec
        # No parameter is fully replicated over the whole mesh unless 1D.
        mlp_kernel = state.params['layers']['mlp']['gate_proj']['kernel']
        assert mlp_kernel.sharding.spec != jax.sharding.PartitionSpec()

    @pytest.mark.slow  # CPU tier-1 budget: full trainer/engine run
    def test_loss_decreases(self):
        trainer = self._trainer()
        trainer.init_state()
        # One fixed batch, repeated: the model must memorize it.
        data_iter = data_lib.synthetic_data(
            trainer.mesh, global_batch_size=8, seq_len=64,
            vocab_size=trainer.model_config.vocab_size)
        batch = next(data_iter)
        first = None
        for _ in range(20):
            metrics = trainer.step(batch)
            if first is None:
                first = float(jax.device_get(metrics['loss']))
        last = float(jax.device_get(metrics['loss']))
        assert last < first - 0.5, (first, last)

    @pytest.mark.slow  # CPU tier-1 budget: full trainer/engine run
    def test_profiler_hook_writes_trace(self, tmp_path, monkeypatch):
        prof_dir = tmp_path / 'profile'
        monkeypatch.setenv('SKYTPU_PROFILE_DIR', str(prof_dir))
        trainer = self._trainer()
        data_iter = data_lib.synthetic_data(
            trainer.mesh, global_batch_size=8, seq_len=64,
            vocab_size=trainer.model_config.vocab_size)
        trainer.train(data_iter, num_steps=4, log_every=10)
        traces = list(prof_dir.rglob('*'))
        assert any(p.is_file() for p in traces), (
            f'no trace files under {prof_dir}')

    @pytest.mark.slow  # CPU tier-1 budget: full trainer/engine run
    def test_grad_accum_matches_single_step(self):
        t1 = self._trainer(grad_accum_steps=1, grad_clip_norm=1e9)
        t2 = self._trainer(grad_accum_steps=2, grad_clip_norm=1e9)
        s1 = t1.init_state()
        # Same init for both; copy buffers (each step donates its own).
        params_copy = jax.tree.map(jnp.array, s1.params)
        t2.state = trainer_lib.TrainState(
            step=jnp.array(s1.step), params=params_copy,
            opt_state=t2.tx.init(params_copy),
            apply_fn=t2._apply_unboxed, tx=t2.tx)
        t2.state_shardings = trainer_lib.TrainState(
            step=t1.state_shardings.step,
            params=t1.state_shardings.params,
            opt_state=t1.state_shardings.opt_state,
            apply_fn=t2._apply_unboxed, tx=t2.tx)
        data_iter = data_lib.synthetic_data(
            t1.mesh, global_batch_size=8, seq_len=64,
            vocab_size=t1.model_config.vocab_size)
        batch = next(data_iter)
        m1 = t1.step(batch)
        m2 = t2.step(batch)
        # Means over microbatches == mean over the full batch (bf16
        # activations: allow rounding-level divergence).
        np.testing.assert_allclose(
            float(jax.device_get(m1['loss'])),
            float(jax.device_get(m2['loss'])), rtol=5e-3)

    @pytest.mark.slow  # CPU tier-1 budget: full trainer/engine run
    def test_checkpoint_roundtrip(self, tmp_path):
        from skypilot_tpu.train import checkpoint as ckpt_lib
        trainer = self._trainer()
        trainer.init_state()
        data_iter = data_lib.synthetic_data(
            trainer.mesh, global_batch_size=8, seq_len=64,
            vocab_size=trainer.model_config.vocab_size)
        trainer.step(next(data_iter))
        manager = ckpt_lib.make_manager(str(tmp_path / 'ckpt'))
        ckpt_lib.save(manager, trainer.state, wait=True)

        trainer2 = self._trainer()
        state2 = ckpt_lib.restore_or_init(manager, trainer2)
        assert int(jax.device_get(state2.step)) == 1
        np.testing.assert_allclose(
            jax.device_get(trainer.state.params['tok_embed']),
            jax.device_get(state2.params['tok_embed']))
