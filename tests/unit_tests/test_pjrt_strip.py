"""Control-plane PJRT-plugin strip: agent/daemon/driver/RPC pythons
skip the sitecustomize accelerator import; USER jobs get the env back.

The silent failure mode of a regression here is user jobs starting
without the accelerator env — jax falls back to CPU far from the
causing change — so the stash round-trip is pinned at three layers:
the shell fragment, the driver restore, and a real bash expansion.
"""
import subprocess

from skypilot_tpu.agent import constants
from skypilot_tpu.agent import job_driver
from skypilot_tpu.agent import rpc as agent_rpc


class TestRestorePluginEnv:

    def test_stash_restored_for_user_job(self):
        env = {constants.PJRT_STASH_ENV: '10.0.0.9',
               constants.PJRT_PLUGIN_ENV: ''}
        job_driver._restore_plugin_env(env)
        assert env[constants.PJRT_PLUGIN_ENV] == '10.0.0.9'
        assert constants.PJRT_STASH_ENV not in env

    def test_blank_var_without_stash_is_dropped(self):
        # Host had no plugin env at all: the strip blanked it; the
        # user env must not carry a confusing empty value.
        env = {constants.PJRT_PLUGIN_ENV: ''}
        job_driver._restore_plugin_env(env)
        assert constants.PJRT_PLUGIN_ENV not in env

    def test_untouched_env_passes_through(self):
        env = {constants.PJRT_PLUGIN_ENV: '10.0.0.9', 'OTHER': 'x'}
        job_driver._restore_plugin_env(env)
        assert env[constants.PJRT_PLUGIN_ENV] == '10.0.0.9'
        assert env['OTHER'] == 'x'


class TestStripPrefix:

    def test_rpc_command_carries_the_prefix(self):
        cmd = agent_rpc.make_rpc_command('ping')
        assert cmd.startswith(constants.PJRT_STRIP_PREFIX)

    def _bash_env_after_prefix(self, outer_env):
        """Run the real prefix through bash; report what a child sees."""
        script = (constants.PJRT_STRIP_PREFIX +
                  f'python3 -c "import os; '
                  f"print(repr(os.environ.get('"
                  f"{constants.PJRT_PLUGIN_ENV}'))); "
                  f"print(repr(os.environ.get('"
                  f"{constants.PJRT_STASH_ENV}')))\"")
        proc = subprocess.run(['bash', '-c', script], env=outer_env,
                              capture_output=True, text=True,
                              check=True)
        plugin, stash = proc.stdout.strip().splitlines()
        return eval(plugin), eval(stash)  # noqa: S307 — repr round-trip

    def test_fresh_spawner_stashes_live_value(self):
        plugin, stash = self._bash_env_after_prefix(
            {'PATH': '/usr/bin:/bin',
             constants.PJRT_PLUGIN_ENV: '10.1.2.3'})
        assert plugin == ''        # stripped for the control plane
        assert stash == '10.1.2.3'  # preserved for user jobs

    def test_stripped_spawner_forwards_inherited_stash(self):
        # A stripped daemon spawning the driver: its blanked live var
        # must NOT clobber the inherited stash.
        plugin, stash = self._bash_env_after_prefix(
            {'PATH': '/usr/bin:/bin',
             constants.PJRT_PLUGIN_ENV: '',
             constants.PJRT_STASH_ENV: '10.1.2.3'})
        assert plugin == ''
        assert stash == '10.1.2.3'

    def test_no_plugin_host_stays_clean(self):
        plugin, stash = self._bash_env_after_prefix(
            {'PATH': '/usr/bin:/bin'})
        assert plugin == ''
        assert stash == ''
