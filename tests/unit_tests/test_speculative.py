"""Speculative decoding: parity-guarded acceptance, draft + n-gram
proposals, and the observability surface.

Speculation changes how many tokens a target forward commits but must
not change WHICH tokens: greedy decode through a speculating engine
must match plain decode bit-for-bit (across llama/gpt2 pairs x
whole/chunked/int8/paged paths, draft and self-drafting modes), and
temperature>0 output must keep the exact plain-decode distribution —
pinned both at the kernel (empirical marginal vs the filtered target
softmax) and end-to-end (seeded output frequencies vs plain decode).

Tier-1/CPU by design: everything here runs under
`JAX_PLATFORMS=cpu -m 'not slow'` (TestTier1Guard enforces that for
every test this PR added).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.infer import engine as engine_lib
from skypilot_tpu.infer import speculative

_COMMON = {'max_seq_len': 128, 'n_layers': 2,
           'dtype': jnp.float32, 'param_dtype': jnp.float32}
_FAMILIES = {
    # GQA 4:2 + rope: the grouped-epilogue branch.
    'llama-tiny': {**_COMMON, 'n_heads': 4, 'n_kv_heads': 2,
                   'dim': 64, 'ffn_dim': 128, 'vocab_size': 96},
    # MHA + learned positions (no rope): the multi-token verify must
    # honor the same cursor contract without rope interpolation.
    'gpt2-tiny': {**_COMMON, 'n_heads': 4, 'dim': 64,
                  'ffn_dim': 128, 'vocab_size': 96},
}
_PS = 8
# Repetitive prompts so n-gram self-drafting actually proposes.
_PROMPTS = [[5, 17, 3, 42, 5, 17, 3, 9, 5, 17, 3], [9, 1, 4, 9, 1, 4]]
_MAX_NEW = 12
_GREEDY = engine_lib.SamplingConfig(max_new_tokens=_MAX_NEW,
                                    temperature=0.0)
_K = 4


def _cbe(family, overrides, **kw):
    kw.setdefault('n_slots', 2)
    kw.setdefault('prefill_bucket', _PS)
    return engine_lib.ContinuousBatchingEngine(
        family, model_overrides=dict(overrides), **kw)


def _spec_kw(family, mode):
    """Engine kwargs for a speculating twin: a SAME-CONFIG draft
    (identical random params via the shared seed, so acceptance is
    high and multi-token commits actually exercise the paths) or
    zero-weight n-gram self-drafting."""
    if mode == 'draft':
        return dict(spec_k=_K, draft_model=family,
                    draft_overrides=dict(_FAMILIES[family]))
    return dict(spec_k=_K)


# ---------------------------------------------------------------------
# n-gram / prompt-lookup proposer (host-side unit tests)
# ---------------------------------------------------------------------

class TestNgramPropose:

    def test_longest_suffix_match_wins(self):
        # suffix [7, 8] occurred earlier, followed by [9, 1].
        ctx = [7, 8, 9, 1, 5, 7, 8]
        assert speculative.ngram_propose(ctx, 4) == [9, 1, 5, 7]

    def test_most_recent_occurrence_wins(self):
        # suffix [2] matches twice; the later one is followed by 6.
        ctx = [2, 5, 2, 6, 2]
        assert speculative.ngram_propose(ctx, 1) == [6]

    def test_no_match_returns_empty(self):
        assert speculative.ngram_propose([1, 2, 3, 4], 4) == []

    def test_k_caps_the_continuation(self):
        ctx = [7, 8, 1, 2, 3, 4, 7, 8]
        assert speculative.ngram_propose(ctx, 2) == [1, 2]

    def test_degenerate_inputs(self):
        assert speculative.ngram_propose([], 4) == []
        assert speculative.ngram_propose([3], 4) == []
        assert speculative.ngram_propose([1, 2, 3], 0) == []


# ---------------------------------------------------------------------
# Acceptance kernel
# ---------------------------------------------------------------------

def _kernel_args(b, k, v, seed=0):
    logits = jax.random.normal(jax.random.PRNGKey(seed),
                               (b, k + 1, v)) * 2.0
    zeros = jnp.zeros((b,), jnp.int32)
    return logits, zeros


class TestAcceptanceKernel:

    def test_greedy_accepts_exactly_the_argmax_prefix(self):
        v, k = 16, 3
        logits, zeros = _kernel_args(1, k, v)
        am = np.asarray(jnp.argmax(logits[0], axis=-1))
        for n_good in range(k + 1):
            drafts = np.array(am[:k])
            if n_good < k:     # break the chain at position n_good
                drafts[n_good] = (drafts[n_good] + 1) % v
            out, counts = speculative.accept_draft_rows(
                logits, jnp.asarray(drafts)[None], jnp.full((1,), k),
                zeros, zeros, jnp.zeros((1,), jnp.float32), zeros,
                jnp.ones((1,), jnp.float32), max_k=0, use_top_p=False)
            assert int(counts[0]) == n_good + 1
            # Committed stream == target greedy continuation: the
            # accepted prefix is the argmax chain and the correction
            # token is the argmax after it.
            want = list(am[:n_good]) + [int(am[n_good])]
            assert list(np.asarray(out[0][:n_good + 1])) == want

    def test_n_prop_caps_acceptance(self):
        v, k = 16, 4
        logits, zeros = _kernel_args(1, k, v)
        am = np.asarray(jnp.argmax(logits[0], axis=-1))
        out, counts = speculative.accept_draft_rows(
            logits, jnp.asarray(am[:k])[None], jnp.full((1,), 2),
            zeros, zeros, jnp.zeros((1,), jnp.float32), zeros,
            jnp.ones((1,), jnp.float32), max_k=0, use_top_p=False)
        # All k proposals match greedy, but only 2 were real: commit
        # caps at 2 accepted + 1 correction.
        assert int(counts[0]) == 3

    def test_stochastic_marginal_matches_filtered_target(self):
        """The provably-unchanged-distribution guarantee, empirically:
        the first committed token's frequency over many seeds matches
        softmax(filter_logits_rows(...)) — the exact distribution
        plain decode samples from."""
        v, k, n = 8, 3, 4000
        logits, _ = _kernel_args(1, k, v, seed=3)
        temps = jnp.array([0.8])
        ks = jnp.array([0])
        ps = jnp.array([1.0])
        target = np.asarray(jax.nn.softmax(engine_lib.filter_logits_rows(
            logits[:, 0], temps, ks, ps, max_k=0, use_top_p=False)))[0]

        def run(seeds):
            b = seeds.shape[0]
            return speculative.accept_draft_rows(
                jnp.tile(logits, (b, 1, 1)),
                jnp.tile(jnp.array([[2, 5, 1]]), (b, 1)),
                jnp.full((b,), k), seeds, jnp.zeros((b,), jnp.int32),
                jnp.tile(temps, b), jnp.tile(ks, b), jnp.tile(ps, b),
                max_k=0, use_top_p=False)

        out, counts = jax.jit(run)(jnp.arange(n, dtype=jnp.int32))
        freq = np.bincount(np.asarray(out[:, 0]), minlength=v) / n
        tv = 0.5 * float(np.abs(freq - target).sum())
        assert tv < 0.05, (tv, freq, target)
        # Some proposals must actually be accepted for the test to
        # exercise the accept branch, and some rejected for the
        # leftover-resample branch.
        acc = np.asarray(counts) - 1
        assert acc.max() > 0 and acc.min() < k

    def test_stochastic_leftover_excludes_rejected_token(self):
        """On rejection the resample comes from the leftover
        distribution — the rejected proposal can never be the
        replacement token (point-mass proposals make the residual
        exactly 'p with d removed')."""
        v, k, n = 8, 1, 512
        logits, _ = _kernel_args(1, k, v, seed=5)
        temps = jnp.array([1.0])
        ks = jnp.array([0])
        ps = jnp.array([1.0])
        draft = 2

        def run(seeds):
            b = seeds.shape[0]
            return speculative.accept_draft_rows(
                jnp.tile(logits, (b, 1, 1)),
                jnp.full((b, k), draft), jnp.full((b,), k), seeds,
                jnp.zeros((b,), jnp.int32), jnp.tile(temps, b),
                jnp.tile(ks, b), jnp.tile(ps, b),
                max_k=0, use_top_p=False)

        out, counts = jax.jit(run)(jnp.arange(n, dtype=jnp.int32))
        out, counts = np.asarray(out), np.asarray(counts)
        rejected = counts == 1
        assert rejected.any()
        assert (out[rejected, 0] != draft).all()


# ---------------------------------------------------------------------
# End-to-end greedy parity (the "accepted prefix must equal target
# greedy" invariant, across cache layouts and both proposer modes)
# ---------------------------------------------------------------------

@pytest.fixture(scope='module', params=sorted(_FAMILIES))
def family_ref(request):
    """Plain (non-speculating) engine = the parity reference."""
    family = request.param
    eng = _cbe(family, _FAMILIES[family])
    return family, eng.params, eng.generate(_PROMPTS, _GREEDY)


@pytest.fixture(scope='module', params=['draft', 'ngram'])
def mode(request):
    return request.param


class TestGreedyParity:

    def test_whole_prefill(self, family_ref, mode):
        family, params, want = family_ref
        eng = _cbe(family, _FAMILIES[family], params=params,
                   **_spec_kw(family, mode))
        assert eng.generate(_PROMPTS, _GREEDY) == want

    def test_chunked_prefill(self, family_ref, mode):
        family, params, want = family_ref
        eng = _cbe(family, _FAMILIES[family], params=params,
                   prefill_chunk=_PS, **_spec_kw(family, mode))
        assert eng.generate(_PROMPTS, _GREEDY) == want

    def test_paged(self, family_ref, mode):
        family, params, want = family_ref
        eng = _cbe(family, _FAMILIES[family], params=params,
                   page_size=_PS, **_spec_kw(family, mode))
        assert eng.generate(_PROMPTS, _GREEDY) == want
        assert eng.allocator_leak_report() is None

    def test_int8_cache(self, family_ref, mode):
        # int8 changes the arithmetic: the reference is the plain
        # int8 engine, speculation must be acceptance-only on top.
        family, params, _ = family_ref
        ref = _cbe(family, _FAMILIES[family], params=params,
                   kv_cache_dtype='int8')
        want = ref.generate(_PROMPTS, _GREEDY)
        eng = _cbe(family, _FAMILIES[family], params=params,
                   kv_cache_dtype='int8', **_spec_kw(family, mode))
        assert eng.generate(_PROMPTS, _GREEDY) == want

    def test_draft_mode_actually_accepts(self, family_ref):
        """Guard against vacuous parity: the same-params draft must
        produce accepted multi-token commits (steps < tokens), or the
        suite is only testing the k=0 fallback."""
        family, params, want = family_ref
        eng = _cbe(family, _FAMILIES[family], params=params,
                   **_spec_kw(family, 'draft'))
        assert eng.generate(_PROMPTS, _GREEDY) == want
        info = eng.speculation_info()
        assert info['acceptance_rate'] > 0.9
        tokens = sum(len(w) for w in want)
        assert info['steps'] < tokens / 2

    def test_ngram_mode_accepts_on_repetitive_prompts(self, family_ref):
        family, params, want = family_ref
        eng = _cbe(family, _FAMILIES[family], params=params,
                   **_spec_kw(family, 'ngram'))
        assert eng.generate(_PROMPTS, _GREEDY) == want
        assert eng.speculation_info()['proposed_tokens'] > 0


class TestSpecEdgeCases:

    def test_max_new_tokens_one(self):
        """The seeded first token IS the whole request: no verify step
        may run (n_prop cap) and the budget must hold exactly."""
        eng = _cbe('llama-tiny', _FAMILIES['llama-tiny'], spec_k=_K)
        ref = _cbe('llama-tiny', _FAMILIES['llama-tiny'],
                   params=eng.params)
        one = engine_lib.SamplingConfig(max_new_tokens=1)
        assert eng.generate(_PROMPTS, one) == ref.generate(_PROMPTS,
                                                           one)
        assert eng.speculation_info()['steps'] == 0

    def test_eos_inside_accepted_run_truncates(self):
        """An eos token committed mid-window ends the request there:
        nothing after it is emitted even when accepted."""
        eng = _cbe('llama-tiny', _FAMILIES['llama-tiny'], spec_k=_K,
                   draft_model='llama-tiny',
                   draft_overrides=dict(_FAMILIES['llama-tiny']))
        ref = _cbe('llama-tiny', _FAMILIES['llama-tiny'],
                   params=eng.params)
        greedy = ref.generate(_PROMPTS[:1], _GREEDY)[0]
        eos = greedy[len(greedy) // 2]   # guaranteed to occur
        cfg = engine_lib.SamplingConfig(max_new_tokens=_MAX_NEW,
                                        eos_id=eos)
        assert eng.generate(_PROMPTS[:1], cfg) == \
            ref.generate(_PROMPTS[:1], cfg)

    def test_vocab_mismatch_rejected_at_init(self):
        """Satellite: draft/target tokenizer-family compatibility is
        validated at engine init with a clear error, instead of
        silently decoding garbage token ids."""
        bad = dict(_FAMILIES['llama-tiny'], vocab_size=48)
        with pytest.raises(ValueError, match='tokenizer family'):
            _cbe('llama-tiny', _FAMILIES['llama-tiny'], spec_k=_K,
                 draft_model='llama-tiny', draft_overrides=bad)

    def test_draft_model_requires_spec_k(self):
        with pytest.raises(ValueError, match='spec_k'):
            _cbe('llama-tiny', _FAMILIES['llama-tiny'],
                 draft_model='llama-tiny',
                 draft_overrides=dict(_FAMILIES['llama-tiny']))

    def test_recover_resets_draft_state(self):
        """After a transient step failure, recover() rebuilds the
        draft cache alongside the target's — subsequent requests must
        still decode with exact greedy parity."""
        eng = _cbe('llama-tiny', _FAMILIES['llama-tiny'], spec_k=_K,
                   draft_model='llama-tiny',
                   draft_overrides=dict(_FAMILIES['llama-tiny']))
        ref = _cbe('llama-tiny', _FAMILIES['llama-tiny'],
                   params=eng.params)
        want = ref.generate(_PROMPTS, _GREEDY)
        assert eng.generate(_PROMPTS, _GREEDY) == want
        eng.recover(RuntimeError('injected'))
        assert eng.generate(_PROMPTS, _GREEDY) == want


# ---------------------------------------------------------------------
# temperature>0: output frequencies match plain decode (e2e)
# ---------------------------------------------------------------------

def test_sampled_output_frequencies_match_plain_decode():
    """Seeded statistical e2e: across many seeds, (a) the first token
    is bit-identical to plain decode (same kernel, same key fold),
    and (b) the frequency distribution of the token AFTER it — the
    accept-or-resample path — matches plain decode within tolerance.
    Both engines' marginals are the same filtered target softmax, so
    a leftover-distribution bug shows up as drift here."""
    ov = dict(_FAMILIES['llama-tiny'], vocab_size=32)
    n = 200
    # max_new=3: the seed token rides prefill, leaving budget for one
    # real proposal per step (max_new=2 would cap n_prop at 0 and the
    # accept branch would never run).
    cfg = [engine_lib.SamplingConfig(max_new_tokens=3, temperature=1.0,
                                     top_k=8, seed=s)
           for s in range(n)]
    prompts = [_PROMPTS[0]] * n

    plain = _cbe('llama-tiny', ov, n_slots=4)
    ref = [plain.generate([p], c)[0] for p, c in zip(prompts, cfg)]
    spec = _cbe('llama-tiny', ov, n_slots=4, params=plain.params,
                spec_k=2, draft_model='llama-tiny',
                draft_overrides=dict(ov))
    got = [spec.generate([p], c)[0] for p, c in zip(prompts, cfg)]

    assert [r[0] for r in ref] == [g[0] for g in got]
    info = spec.speculation_info()
    assert info['accepted_tokens'] > 0      # accept branch exercised
    assert info['accepted_tokens'] < info['proposed_tokens']  # reject too
    f_ref = np.bincount([r[1] for r in ref], minlength=32) / n
    f_got = np.bincount([g[1] for g in got], minlength=32) / n
    tv = 0.5 * float(np.abs(f_ref - f_got).sum())
    # Two independent n=200 draws from the same 8-support distribution
    # land at TV ~= 0.1; a wrong acceptance rule (e.g. unfiltered
    # probabilities or a missing leftover mask) shifts mass by far
    # more than the 0.25 gate.
    assert tv < 0.25, (tv, f_ref, f_got)


# ---------------------------------------------------------------------
# Server surface: flags, /health?verbose=1 block, /metrics series
# ---------------------------------------------------------------------

def test_server_health_and_metrics_surface():
    import json
    import threading
    import urllib.request

    from skypilot_tpu import observability
    from skypilot_tpu.infer.server import InferenceServer
    from skypilot_tpu.observability import metrics as metrics_lib

    reg = metrics_lib.Registry()
    srv = InferenceServer(
        model='llama-tiny', port=0, host='127.0.0.1',
        max_batch_size=2,
        model_overrides=dict(_FAMILIES['llama-tiny'],
                             max_seq_len=64),
        allow_random_weights=True, page_size=_PS, spec_k=2,
        registry=reg)
    srv.start()
    threading.Thread(
        target=lambda s=srv._server: s.serve_forever(poll_interval=0.05),
        daemon=True).start()
    base = f'http://127.0.0.1:{srv.port}'
    try:
        body = json.dumps(dict(
            model='llama-tiny',
            prompt='abcabcabc', max_tokens=8)).encode()
        resp = urllib.request.urlopen(
            urllib.request.Request(base + '/v1/completions', data=body),
            timeout=120)
        assert resp.status == 200

        health = json.loads(urllib.request.urlopen(
            base + '/health?verbose=1', timeout=30).read())
        spec = health['speculation']
        assert spec['mode'] == 'ngram' and spec['spec_k'] == 2
        assert spec['steps'] >= 1

        text = urllib.request.urlopen(base + '/metrics',
                                      timeout=30).read().decode()
        scraped = {line.split(' ')[2] for line in text.splitlines()
                   if line.startswith('# TYPE ')}
        # A speculating replica's scrape includes the spec series —
        # and still nothing outside the contract.
        for name in ('skytpu_spec_steps_total',
                     'skytpu_spec_proposed_tokens_total',
                     'skytpu_spec_accepted_tokens_total',
                     'skytpu_spec_accepted_tokens',
                     'skytpu_spec_draft_steps_total'):
            assert name in scraped, name
        assert scraped <= observability.METRIC_CONTRACT, \
            scraped - observability.METRIC_CONTRACT
        parsed = metrics_lib.parse_exposition(text)
        assert metrics_lib.sample_value(
            parsed, 'skytpu_spec_steps_total') >= 1
    finally:
        srv.shutdown()


def test_traces_carry_tokens_per_step():
    """Satellite: per-request step accounting no longer assumes one
    token per step — the trace separates decode_steps from
    output_tokens, and a speculating engine shows tokens/step > 1."""
    eng = _cbe('llama-tiny', _FAMILIES['llama-tiny'], spec_k=_K,
               draft_model='llama-tiny',
               draft_overrides=dict(_FAMILIES['llama-tiny']))
    eng.generate(_PROMPTS[:1], _GREEDY)
    done = [t for t in eng.traces.recent(5)
            if t['state'] == 'finished'][0]
    assert done['output_tokens'] == _MAX_NEW
    assert 0 < done['decode_steps'] < _MAX_NEW
    assert done['tokens_per_step'] > 1.0


# Test surfaces this PR added: scanned by the tier-1 guard below.
_PR_TEST_SURFACES = {
    'test_speculative.py': None,         # whole file
    'test_bench_capture.py': ['test_decode_smoke_speculative_arm'],
}


class TestTier1Guard:
    """Every test this PR added must run in the tier-1 lane: CPU
    backend, no `slow` marker, no TPU gating — the parity and
    distribution guarantees are only guarantees if CI executes them."""

    def test_runs_on_cpu_backend(self):
        assert jax.default_backend() == 'cpu'

    def test_new_tests_not_slow_marked(self):
        import pathlib
        here = pathlib.Path(__file__).parent
        for fname, surfaces in _PR_TEST_SURFACES.items():
            text = (here / fname).read_text()
            if surfaces is None:
                scopes = [text]
            else:
                scopes = []
                for name in surfaces:
                    assert name in text, (fname, name)
                    scopes.append(text[text.index(name):])
            slow, tpu = 'mark.' + 'slow', 'requires' + '_tpu'
            for scope in scopes:
                assert slow not in scope, fname
                assert tpu not in scope, fname
