"""utils/retry: backoff math, fatal channels, and budget awareness.

The retry loop backs three production call sites (mesh backend init,
the bench attempt ladder, the decode-loop supervisor's restart nap),
so its contract is pinned here independently of any of them.
"""
import random

import pytest

from skypilot_tpu.utils import retry as retry_lib


def test_compute_delay_exponential_no_jitter():
    delays = [retry_lib.compute_delay(k, 1.5, factor=2.0,
                                      jitter='none')
              for k in range(4)]
    assert delays == [1.5, 3.0, 6.0, 12.0]


def test_compute_delay_caps_at_max():
    assert retry_lib.compute_delay(10, 1.0, factor=2.0,
                                   max_delay_s=7.0,
                                   jitter='none') == 7.0


def test_compute_delay_full_jitter_within_envelope():
    rng = random.Random(7)
    for k in range(6):
        d = retry_lib.compute_delay(k, 2.0, factor=2.0,
                                    max_delay_s=16.0, jitter='full',
                                    rng=rng)
        assert 0.0 <= d <= min(2.0 * 2 ** k, 16.0)


def test_compute_delay_rejects_unknown_jitter():
    with pytest.raises(ValueError, match='jitter'):
        retry_lib.compute_delay(0, 1.0, jitter='half')


def test_succeeds_after_failures_and_sleeps_backoff():
    sleeps = []
    calls = {'n': 0}

    def _fn():
        calls['n'] += 1
        if calls['n'] < 3:
            raise RuntimeError(f'boom {calls["n"]}')
        return 'ok'

    out = retry_lib.retry_with_backoff(
        _fn, max_attempts=5, base_delay_s=2.0, factor=2.0,
        jitter='none', sleep=sleeps.append)
    assert out == 'ok'
    assert calls['n'] == 3
    assert sleeps == [2.0, 4.0]  # naps before attempts 2 and 3 only


def test_exhausted_attempts_raise_retry_error_with_cause():
    last = RuntimeError('always')

    def _fn():
        raise last

    with pytest.raises(retry_lib.RetryError,
                       match='after 3 attempt') as ei:
        retry_lib.retry_with_backoff(
            _fn, max_attempts=3, base_delay_s=0.0, jitter='none',
            sleep=lambda _s: None, describe='op')
    assert ei.value.attempts == 3
    assert ei.value.last is last
    assert ei.value.__cause__ is last


def test_fatal_exceptions_raise_through_unchanged():
    class Hang(RuntimeError):
        pass

    def _fn():
        raise Hang('wedged')

    calls = {'n': 0}

    def _count_and_raise():
        calls['n'] += 1
        raise Hang('wedged')

    # Fatal wins even when the type also matches retry_on.
    with pytest.raises(Hang):
        retry_lib.retry_with_backoff(
            _count_and_raise, max_attempts=5,
            retry_on=(RuntimeError,), fatal=(Hang,),
            sleep=lambda _s: None)
    assert calls['n'] == 1  # never retried


def test_non_retryable_exceptions_raise_through():
    def _fn():
        raise KeyError('nope')

    with pytest.raises(KeyError):
        retry_lib.retry_with_backoff(
            _fn, max_attempts=5, retry_on=(RuntimeError,),
            sleep=lambda _s: None)


def test_budget_exhausted_before_first_attempt():
    calls = {'n': 0}

    def _fn():
        calls['n'] += 1

    with pytest.raises(retry_lib.RetryError,
                       match='budget exhausted') as ei:
        retry_lib.retry_with_backoff(
            _fn, max_attempts=3, remaining_s=lambda: 10.0,
            min_attempt_s=60.0, sleep=lambda _s: None)
    assert calls['n'] == 0
    assert ei.value.attempts == 0
    assert ei.value.last is None


def test_budget_skips_nap_but_keeps_attempting():
    """The nap would starve the next attempt -> retry back-to-back."""
    sleeps = []
    calls = {'n': 0}

    def _fn():
        calls['n'] += 1
        raise RuntimeError('x')

    with pytest.raises(retry_lib.RetryError):
        retry_lib.retry_with_backoff(
            _fn, max_attempts=3, base_delay_s=600.0, factor=1.0,
            jitter='none',
            remaining_s=lambda: 400.0,  # attempt fits, nap does not
            min_attempt_s=150.0, sleep=sleeps.append)
    assert calls['n'] == 3
    assert sleeps == []  # every nap skipped, never slept the 600


def test_budget_gives_up_mid_ladder():
    """Budget shrinks below min_attempt_s after the first failure."""
    budget = {'left': 200.0}
    calls = {'n': 0}

    def _fn():
        calls['n'] += 1
        budget['left'] = 10.0  # the attempt consumed the budget
        raise RuntimeError('x')

    with pytest.raises(retry_lib.RetryError) as ei:
        retry_lib.retry_with_backoff(
            _fn, max_attempts=5, base_delay_s=0.0, jitter='none',
            remaining_s=lambda: budget['left'], min_attempt_s=150.0,
            sleep=lambda _s: None)
    assert calls['n'] == 1
    assert ei.value.attempts == 1


def test_on_failure_hook_sees_retry_decisions():
    seen = []

    def _fn():
        raise RuntimeError('x')

    with pytest.raises(retry_lib.RetryError):
        retry_lib.retry_with_backoff(
            _fn, max_attempts=3, base_delay_s=5.0, factor=1.0,
            jitter='none',
            on_failure=lambda a, e, will, d: seen.append((a, will, d)),
            sleep=lambda _s: None)
    assert seen == [(1, True, 5.0), (2, True, 5.0), (3, False, 0.0)]


def test_max_attempts_must_be_positive():
    with pytest.raises(ValueError, match='max_attempts'):
        retry_lib.retry_with_backoff(lambda: None, max_attempts=0)


class _ShedError(RuntimeError):
    """Carries retry_after_s like an HTTP 503 with Retry-After."""

    def __init__(self, retry_after_s):
        super().__init__('shed')
        self.retry_after_s = retry_after_s


def test_retry_after_floors_the_backoff_nap():
    """A server-paced exception must never be retried EARLIER than the
    server asked — the computed backoff (here 0.1s) is floored up."""
    sleeps = []
    calls = {'n': 0}

    def _fn():
        calls['n'] += 1
        if calls['n'] < 3:
            raise _ShedError(7.5)
        return 'ok'

    out = retry_lib.retry_with_backoff(
        _fn, max_attempts=4, base_delay_s=0.1, factor=1.0,
        jitter='none', sleep=sleeps.append)
    assert out == 'ok'
    assert sleeps == [7.5, 7.5]


def test_retry_after_does_not_shorten_longer_backoff():
    sleeps = []

    def _fn():
        raise _ShedError(0.5)

    with pytest.raises(retry_lib.RetryError):
        retry_lib.retry_with_backoff(
            _fn, max_attempts=3, base_delay_s=60.0, factor=1.0,
            jitter='none', sleep=sleeps.append)
    assert sleeps == [60.0, 60.0]  # max(backoff, retry_after)


def test_retry_after_that_starves_the_budget_gives_up():
    """Under a budget, a floored nap that would leave less than
    min_attempt_s ends the loop — retrying before the server's pace is
    known-useless, so no early hammer and no wasted attempt."""
    sleeps = []
    calls = {'n': 0}

    def _fn():
        calls['n'] += 1
        raise _ShedError(300.0)

    with pytest.raises(retry_lib.RetryError) as ei:
        retry_lib.retry_with_backoff(
            _fn, max_attempts=5, base_delay_s=0.1, jitter='none',
            remaining_s=lambda: 200.0, min_attempt_s=10.0,
            sleep=sleeps.append)
    assert calls['n'] == 1        # no back-to-back early retry
    assert sleeps == []           # and no nap it could not afford
    assert ei.value.attempts == 1
