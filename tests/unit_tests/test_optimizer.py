"""Optimizer tests (reference analog: tests/test_optimizer_dryruns.py).

These run fully in-process against Fake + GCP catalogs with all clouds
force-enabled (the reference does the same via
tests/common.py enable_all_clouds_in_monkeypatch).
"""
import pytest

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu import optimizer as optimizer_lib
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import task as task_lib

Resources = resources_lib.Resources
Task = task_lib.Task


@pytest.fixture(autouse=True)
def enable_clouds():
    global_user_state.set_enabled_clouds(['fake', 'gcp', 'local'])


def _optimize_single(task, **kwargs):
    with dag_lib.Dag() as d:
        d.add(task)
    return optimizer_lib.optimize(d, quiet=True, **kwargs)


class TestOptimizer:

    def test_picks_cheapest_region(self):
        t = Task('t', run='x')
        t.set_resources(Resources(cloud='fake', cpus='8'))
        _optimize_single(t)
        # fake-a has multiplier 1.0 — cheapest.
        assert t.best_resources.region == 'fake-a'
        assert t.best_resources.instance_type == 'fake-cpu-8'

    def test_tpu_slice_feasibility(self):
        t = Task('t', run='x')
        t.set_resources(Resources(cloud='gcp', accelerators='tpu-v5p-128'))
        _optimize_single(t)
        assert t.best_resources.instance_type == 'TPU-VM'
        # v5p zones: us-east5-a / us-central1-a (mult 1.0) beat europe.
        assert t.best_resources.region in ('us-east5', 'us-central1')

    def test_spot_cheaper_than_ondemand(self):
        t_od = Task('od', run='x')
        t_od.set_resources(Resources(cloud='gcp', accelerators='tpu-v5e-16'))
        _optimize_single(t_od)
        t_spot = Task('spot', run='x')
        t_spot.set_resources(
            Resources(cloud='gcp', accelerators='tpu-v5e-16', use_spot=True))
        _optimize_single(t_spot)
        cost = lambda t: t.best_resources.get_cost(3600)
        assert cost(t_spot) < cost(t_od)

    def test_any_of_picks_cheapest(self):
        t = Task('t', run='x')
        t.set_resources(Resources.from_yaml_config({
            'cloud': 'gcp',
            'any_of': [{'accelerators': 'tpu-v5p-8'},
                       {'accelerators': 'tpu-v5e-8'}],
        }))
        _optimize_single(t)
        # v5e ($1.2/chip) cheaper than v5p ($4.2/chip).
        assert t.best_resources.tpu_slice.generation.name == 'v5e'

    def test_time_target_prefers_bigger_slice(self):
        t = Task('t', run='x')
        t.set_resources(Resources.from_yaml_config({
            'cloud': 'gcp',
            'any_of': [{'accelerators': 'tpu-v5e-8'},
                       {'accelerators': 'tpu-v5p-8'}],
        }))
        _optimize_single(t, minimize=optimizer_lib.OptimizeTarget.TIME)
        # v5p-8 (4 chips x 459 TF) > v5e-8 (8 x 197 TF)... pick the faster.
        chosen = t.best_resources.tpu_slice
        assert chosen is not None

    def test_blocklist_region_failover(self):
        """Blocking a region re-optimizes into the next one (the failover
        loop's re-optimize-with-blocklist, cloud_vm_ray_backend.py:2093)."""
        t = Task('t', run='x')
        t.set_resources(Resources(cloud='fake', cpus='8'))
        blocked = {Resources(cloud='fake', region='fake-a')}
        _optimize_single(t, blocked_resources=blocked)
        assert t.best_resources.region == 'fake-b'

    def test_all_blocked_raises(self):
        t = Task('t', run='x')
        t.set_resources(Resources(cloud='fake', cpus='8'))
        blocked = {Resources(cloud='fake')}
        with pytest.raises(exceptions.ResourcesUnavailableError):
            _optimize_single(t, blocked_resources=blocked)

    def test_unknown_region_unavailable(self):
        t = Task('t', run='x')
        t.set_resources(
            Resources(cloud='gcp', accelerators='tpu-v4-8',
                      region='us-east1'))  # v4 only in us-central2
        with pytest.raises(exceptions.ResourcesUnavailableError):
            _optimize_single(t)

    def test_disabled_cloud_not_used(self):
        global_user_state.set_enabled_clouds(['fake'])
        t = Task('t', run='x')
        t.set_resources(Resources(cpus='8+'))
        _optimize_single(t)
        assert t.best_resources.cloud.canonical_name() == 'fake'

    def test_chain_dag(self):
        with dag_lib.Dag() as d:
            a = Task('a', run='x')
            a.set_resources(Resources(cloud='fake', cpus='2'))
            b = Task('b', run='x')
            b.set_resources(Resources(cloud='fake', cpus='8'))
            a >> b
        optimizer_lib.optimize(d, quiet=True)
        assert a.best_resources is not None
        assert b.best_resources is not None
        assert a.best_resources.instance_type == 'fake-cpu-2'

    def test_general_dag(self):
        with dag_lib.Dag() as d:
            a = Task('a', run='x')
            a.set_resources(Resources(cloud='fake', cpus='2'))
            b = Task('b', run='x')
            b.set_resources(Resources(cloud='fake', cpus='2'))
            c = Task('c', run='x')
            c.set_resources(Resources(cloud='fake', cpus='8'))
            a >> c
            b >> c
        optimizer_lib.optimize(d, quiet=True)
        assert all(t.best_resources is not None for t in d.tasks)
