"""Real 2-process jax.distributed rendezvous through the launcher's
env contract.

Every other distributed test uses a single-process virtual mesh; this
one actually rendezvouses two OS processes over a localhost
coordinator — the seam the gang driver's env injection feeds
(train/launcher.py maybe_initialize_distributed, agent/constants.py),
the TPU-native analog of the torchrun c10d rendezvous the reference's
recipes exercise (examples/torch_ddp_benchmark/).

Each rank runs a cross-process allgather and a psum-style reduction;
the parent asserts BOTH ranks computed identical, correct results —
i.e. the collective really crossed the process boundary.
"""
import json
import os
import socket
import subprocess
import sys

import pytest

_WORKER = r'''
import json, os, sys

# CPU backend, forced via jax.config (env alone is not enough on
# tunneled-TPU hosts — sitecustomize registers the tunnel platform).
import jax
jax.config.update('jax_platforms', 'cpu')

from skypilot_tpu.train import launcher

assert launcher.maybe_initialize_distributed(), 'env contract not seen'
import jax.numpy as jnp
from jax.experimental import multihost_utils

info = launcher.process_info()
assert jax.process_count() == info['num_processes'] == 2
assert jax.process_index() == info['process_id']

# Cross-process collective: allgather each rank's contribution, then
# reduce.  If the rendezvous silently fell back to single-process,
# the gathered vector would be missing the peer's value.
mine = jnp.array([float(10 + jax.process_index())])
gathered = multihost_utils.process_allgather(mine)
total = float(gathered.sum())
print(json.dumps({'rank': jax.process_index(),
                  'gathered': sorted(float(x) for x in gathered.ravel()),
                  'sum': total}))
'''


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


def _spawn_ranks(port: int):
    from skypilot_tpu.agent import constants
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            constants.ENV_COORDINATOR_ADDR: f'127.0.0.1:{port}',
            constants.ENV_NUM_PROCESSES: '2',
            constants.ENV_PROCESS_ID: str(rank),
            # The tunnel plugin must not be imported in the workers.
            'JAX_PLATFORMS': 'cpu',
        })
        env.pop(constants.PJRT_PLUGIN_ENV, None)
        procs.append(subprocess.Popen(
            [sys.executable, '-c', _WORKER],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True))
    outs = [p.communicate(timeout=150) for p in procs]
    return procs, outs


def test_two_process_rendezvous_psum():
    # One retry on a fresh port: _free_port has a TOCTOU window (the
    # port can be taken between probe and the coordinator's bind).
    for attempt in range(2):
        procs, outs = _spawn_ranks(_free_port())
        if all(p.returncode == 0 for p in procs):
            break
        if attempt == 0:
            continue
        for rank, (proc, (out, err)) in enumerate(zip(procs, outs)):
            assert proc.returncode == 0, \
                f'rank {rank} failed:\n{err[-2000:]}'
    results = {}
    for rank, (out, _err) in enumerate(outs):
        line = [l for l in out.splitlines() if l.startswith('{')][-1]
        results[rank] = json.loads(line)
    # Both ranks saw BOTH contributions and agree on the reduction.
    for rank, res in results.items():
        assert res['rank'] == rank
        assert res['gathered'] == [10.0, 11.0], res
        assert res['sum'] == 21.0
