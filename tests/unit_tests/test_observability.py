"""Telemetry layer: metric semantics, Prometheus exposition, request
traces, engine lifecycle accounting, the metric-name contract, and the
overhead guard (PR: engine telemetry)."""
import json
import logging
import math
import re

import jax
import pytest

from skypilot_tpu import sky_logging
from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.observability import tracing as tracing_lib


# ---------------------------------------------------------------------
# Metric semantics
# ---------------------------------------------------------------------

def test_counter_semantics():
    reg = metrics_lib.Registry()
    c = reg.counter('skytpu_test_total', 'help')
    assert c.value == 0.0
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)


def test_gauge_semantics():
    reg = metrics_lib.Registry()
    g = reg.gauge('skytpu_test_gauge', 'help')
    g.set(4)
    g.inc()
    g.dec(2)
    assert g.value == 3.0


def test_histogram_semantics():
    reg = metrics_lib.Registry()
    h = reg.histogram('skytpu_test_seconds', 'help',
                      buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.1, 0.5, 5.0, 100.0):
        h.observe(v)
    assert h.count == 5
    assert math.isclose(h.sum, 105.65)
    text = reg.expose()
    # Cumulative le buckets: 0.1 holds <=0.1 (two observations).
    assert 'skytpu_test_seconds_bucket{le="0.1"} 2' in text
    assert 'skytpu_test_seconds_bucket{le="1"} 3' in text
    assert 'skytpu_test_seconds_bucket{le="10"} 4' in text
    assert 'skytpu_test_seconds_bucket{le="+Inf"} 5' in text
    assert 'skytpu_test_seconds_count 5' in text


def test_labels_and_validation():
    reg = metrics_lib.Registry()
    c = reg.counter('skytpu_labeled_total', 'help',
                    labelnames=('route', 'code'))
    c.labels(route='/health', code='200').inc()
    c.labels(route='/health', code='200').inc()
    c.labels(route='/generate', code='500').inc()
    assert c.value_for(route='/health', code='200') == 2.0
    with pytest.raises(ValueError):
        c.labels(route='/health')               # missing label
    with pytest.raises(ValueError):
        c.labels(route='/h', code='1', x='y')   # unknown label
    plain = reg.counter('skytpu_plain_total', 'help')
    with pytest.raises(ValueError):
        plain.labels(route='x')                 # unlabeled metric
    with pytest.raises(ValueError):
        reg.histogram('skytpu_bad_seconds', 'help', labelnames=('le',))


def test_label_cardinality_cap_collapses_to_overflow():
    reg = metrics_lib.Registry(max_label_sets=3)
    c = reg.counter('skytpu_capped_total', 'help', labelnames=('k',))
    for i in range(10):
        c.labels(k=f'v{i}').inc()
    text = reg.expose()
    # 3 real children + the overflow child soaking everything else.
    assert text.count('skytpu_capped_total{') == 4
    assert c.value_for(k='_overflow') == 7.0


def test_registry_get_or_create_and_conflicts():
    reg = metrics_lib.Registry()
    a = reg.counter('skytpu_shared_total', 'help')
    b = reg.counter('skytpu_shared_total', 'other help')
    assert a is b
    with pytest.raises(ValueError):
        reg.gauge('skytpu_shared_total', 'x')   # type conflict
    with pytest.raises(ValueError):
        reg.counter('skytpu_shared_total', 'x', labelnames=('l',))
    with pytest.raises(ValueError):
        reg.counter('not a name!', 'x')
    assert reg.get('skytpu_shared_total') is a
    assert reg.get('missing') is None
    assert reg.names() == ['skytpu_shared_total']
    reg.unregister('skytpu_shared_total')
    assert reg.names() == []


def test_disabled_registry_is_noop():
    reg = metrics_lib.Registry(enabled=False)
    c = reg.counter('skytpu_off_total', 'help')
    g = reg.gauge('skytpu_off_gauge', 'help')
    h = reg.histogram('skytpu_off_seconds', 'help')
    c.inc(5)
    g.set(3)
    h.observe(1.0)
    assert c.value == 0.0 and g.value == 0.0 and h.count == 0
    reg.set_enabled(True)
    c.inc(5)
    assert c.value == 5.0


# ---------------------------------------------------------------------
# Exposition format (golden test via a minimal Prometheus parser)
# ---------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)$')


def _parse_prometheus(text):
    """Minimal v0.0.4 text parser: {family: type}, {(name, labels):
    value}.  Raises on any line that is not a comment or a sample."""
    types, helps, samples = {}, {}, {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith('# TYPE '):
            _, _, name, typ = line.split(' ', 3)
            assert typ in ('counter', 'gauge', 'histogram'), line
            types[name] = typ
        elif line.startswith('# HELP '):
            _, _, name, help_text = line.split(' ', 3)
            helps[name] = help_text
        else:
            m = _SAMPLE_RE.match(line)
            assert m, f'unparseable exposition line: {line!r}'
            key = (m.group(1), m.group(2) or '')
            assert key not in samples, f'duplicate sample: {key}'
            samples[key] = float(m.group(3))
    return types, helps, samples


def test_exposition_round_trips_through_parser():
    reg = metrics_lib.Registry()
    reg.counter('skytpu_events_total', 'Events.').inc(3)
    reg.gauge('skytpu_depth', 'Depth "quoted" help').set(2.5)
    h = reg.histogram('skytpu_lat_seconds', 'Latency.',
                      labelnames=('route',), buckets=(0.5, 5.0))
    h.labels(route='/a"b\\c').observe(0.1)
    h.labels(route='/a"b\\c').observe(1.0)
    types, helps, samples = _parse_prometheus(reg.expose())
    assert types == {'skytpu_events_total': 'counter',
                     'skytpu_depth': 'gauge',
                     'skytpu_lat_seconds': 'histogram'}
    assert helps['skytpu_events_total'] == 'Events.'
    assert samples[('skytpu_events_total', '')] == 3.0
    assert samples[('skytpu_depth', '')] == 2.5
    # Label values escape quotes/backslashes per the text format.
    lbl = '{route="/a\\"b\\\\c"'
    bucket_keys = [k for k in samples
                   if k[0] == 'skytpu_lat_seconds_bucket']
    assert all(k[1].startswith(lbl) for k in bucket_keys)
    by_le = {k[1]: v for k, v in samples.items()
             if k[0] == 'skytpu_lat_seconds_bucket'}
    vals = [by_le[f'{lbl},le="0.5"}}'], by_le[f'{lbl},le="5"}}'],
            by_le[f'{lbl},le="+Inf"}}']]
    assert vals == [1.0, 2.0, 2.0]          # cumulative, +Inf == count
    assert samples[('skytpu_lat_seconds_count', lbl + '}')] == 2.0
    assert math.isclose(
        samples[('skytpu_lat_seconds_sum', lbl + '}')], 1.1)


# ---------------------------------------------------------------------
# JSON logging satellite
# ---------------------------------------------------------------------

def test_json_formatter_env_switch(monkeypatch):
    monkeypatch.delenv('SKYTPU_LOG_JSON', raising=False)
    assert not isinstance(sky_logging.make_formatter(),
                          sky_logging.JsonFormatter)
    monkeypatch.setenv('SKYTPU_LOG_JSON', '1')
    fmt = sky_logging.make_formatter()
    assert isinstance(fmt, sky_logging.JsonFormatter)
    rec = logging.LogRecord('skypilot_tpu.x', logging.WARNING,
                            'f.py', 1, 'boom %s', ('now',), None)
    payload = json.loads(fmt.format(rec))
    assert payload == {'ts': pytest.approx(rec.created, abs=1e-3),
                       'level': 'WARNING',
                       'logger': 'skypilot_tpu.x',
                       'msg': 'boom now'}


# ---------------------------------------------------------------------
# Traces
# ---------------------------------------------------------------------

def test_trace_store_lifecycle_and_jsonl_sink(tmp_path):
    sink = tmp_path / 'traces.jsonl'
    store = tracing_lib.TraceStore(capacity=2, jsonl_path=str(sink))
    store.begin(1, prompt_tokens=7)
    store.event(1, 'admitted', shared_prefix_tokens=3)
    store.event(1, 'prefill_chunk')
    store.event(1, 'prefill_done')
    store.event(1, 'first_token')
    trace = store.finish(1, 'finished', output_tokens=4)
    assert trace is not None and trace.state == 'finished'
    assert store.finish(1, 'cancelled') is None      # idempotent
    assert trace.ttft_seconds() is not None
    assert trace.queue_seconds() is not None
    d = trace.to_dict()
    assert d['prompt_tokens'] == 7 and d['output_tokens'] == 4
    assert d['shared_prefix_tokens'] == 3
    # Ring capacity bounds completed traces.
    for rid in (2, 3, 4):
        store.begin(rid)
        store.finish(rid, 'cancelled')
    assert len(store.recent(100)) == 2
    assert store.inflight_count == 0
    events = [json.loads(line) for line in
              sink.read_text().splitlines()]
    names = [e['event'] for e in events if e['rid'] == 1]
    assert names[0] == 'queued' and 'finished' in names


def test_trace_abort_all():
    store = tracing_lib.TraceStore(capacity=8)
    store.begin(1)
    store.begin(2)
    dropped = store.abort_all()
    assert sorted(t.request_id for t in dropped) == [1, 2]
    assert store.inflight_count == 0
    assert all(t['state'] == 'aborted' for t in store.recent())


def test_trace_abort_all_mixed_lifecycle_states():
    """abort_all must terminate traces wherever they are in the
    lifecycle — decoding, prefilling, or still queued — and preserve
    the timeline facts each had already accrued."""
    store = tracing_lib.TraceStore(capacity=8)
    store.begin(1)                         # will reach decoding
    store.event(1, 'admitted')
    store.event(1, 'prefill_done')
    store.event(1, 'first_token')
    store.begin(2)                         # will reach prefilling
    store.event(2, 'admitted')
    store.begin(3)                         # stays queued
    dropped = store.abort_all(error='RuntimeError("wedged")')
    assert sorted(t.request_id for t in dropped) == [1, 2, 3]
    assert store.inflight_count == 0
    by_id = {t.request_id: t for t in dropped}
    assert all(t.state == 'aborted' for t in dropped)
    assert all(t.error == 'RuntimeError("wedged")' for t in dropped)
    # The decoding trace keeps its TTFT; the queued one never got one.
    assert by_id[1].ttft_seconds() is not None
    assert by_id[2].admitted_ts is not None
    assert by_id[2].first_token_ts is None
    assert by_id[3].admitted_ts is None
    # A second abort_all is a no-op (nothing left in flight).
    assert store.abort_all() == []


def test_trace_jsonl_sink_close_flushes_and_reopens(tmp_path):
    sink = tmp_path / 'traces.jsonl'
    store = tracing_lib.TraceStore(capacity=4, jsonl_path=str(sink))
    store.begin(1)
    store.finish(1, 'finished')
    store.close()
    lines = [json.loads(l) for l in sink.read_text().splitlines()]
    assert [e['event'] for e in lines] == ['queued', 'finished']
    # The sink reopens in append mode after close(): late events from
    # a drain race land in the file instead of being dropped.
    store.begin(2)
    store.finish(2, 'cancelled')
    store.close()
    lines = [json.loads(l) for l in sink.read_text().splitlines()]
    assert [e['event'] for e in lines] == ['queued', 'finished',
                                           'queued', 'cancelled']
    store.close()                          # idempotent


def test_trace_completed_ring_eviction_boundary():
    """capacity bounds COMPLETED traces only; eviction is exact at the
    boundary (oldest out as the (capacity+1)-th completion lands) and
    in-flight traces never count toward it."""
    store = tracing_lib.TraceStore(capacity=2)
    for rid in (1, 2):
        store.begin(rid)
        store.finish(rid, 'finished')
    assert [t['request_id'] for t in store.recent(10)] == [2, 1]
    assert store.get(1) is not None        # at capacity, not past it
    store.begin(3)
    store.finish(3, 'finished')            # capacity+1: evicts rid 1
    assert [t['request_id'] for t in store.recent(10)] == [3, 2]
    assert store.get(1) is None
    store.begin(4)                         # in-flight: outside the ring
    assert [t['request_id'] for t in store.recent(10)] == [4, 3, 2]
    assert store.get(2) is not None
    store.finish(4, 'finished')            # completes: now evicts rid 2
    assert store.get(2) is None


# ---------------------------------------------------------------------
# Distributed tracing primitives (spans + context propagation)
# ---------------------------------------------------------------------

def test_trace_context_header_round_trip():
    hdr = tracing_lib.format_trace_context('req-1a2b', 'span-3c4d')
    assert hdr == 'req-1a2b/span-3c4d'
    assert tracing_lib.parse_trace_context(hdr) == ('req-1a2b',
                                                    'span-3c4d')


@pytest.mark.parametrize('bad', [
    None, '', 'noseparator', 'a/b/c', 'sp ace/x', 'a/',
    'x' * 65 + '/y', 'ok/' + 'y' * 65,
])
def test_trace_context_malformed_values_are_absent(bad):
    assert tracing_lib.parse_trace_context(bad) is None


def test_span_store_parenting_and_order():
    store = tracing_lib.SpanStore()
    root = store.start('req-1', 'router.request', route='/generate')
    child = store.start('req-1', 'router.attempt',
                        parent_id=root.span_id, url='http://r1')
    child.end(status='retry', outcome='conn_error')
    root.end(status='ok', attempts=1)
    spans = store.get('req-1')
    assert [s['name'] for s in spans] == ['router.request',
                                         'router.attempt']
    assert spans[1]['parent_id'] == root.span_id
    assert spans[1]['status'] == 'retry'
    assert spans[1]['attrs']['outcome'] == 'conn_error'
    assert spans[0]['duration_seconds'] is not None
    # end() is idempotent: the first end wins the timestamp.
    first_end = root.end_ts
    root.end(status='late')
    assert root.end_ts == first_end
    assert store.get('missing') == []


def test_span_store_evicts_whole_oldest_traces():
    store = tracing_lib.SpanStore(capacity=2)
    for tid in ('t1', 't2', 't3'):
        store.start(tid, 'root')
        store.start(tid, 'child')
    assert store.trace_count == 2
    assert store.get('t1') == []           # evicted as a unit
    assert len(store.get('t2')) == 2       # survivor keeps all spans
    docs = store.recent(10)
    assert [d['trace_id'] for d in docs] == ['t3', 't2']
    # Re-starting an evicted trace id opens a fresh trace.
    store.start('t1', 'root')
    assert store.get('t2') == []           # t2 was oldest; now evicted


# ---------------------------------------------------------------------
# Flight recorder (EventRing)
# ---------------------------------------------------------------------

def test_event_ring_contract_capacity_and_counter():
    from skypilot_tpu.observability import events as events_lib
    reg = metrics_lib.Registry()
    ring = events_lib.EventRing(capacity=3, registry=reg,
                                source='router')
    with pytest.raises(ValueError):
        ring.record('not_a_real_event')
    for i in range(5):
        ring.record('chaos_injection', point=f'p{i}')
    ring.record('breaker_transition', url='http://r1', state='open')
    assert len(ring) == 3                  # ring stays bounded
    assert ring.total_recorded == 6        # monotonic across eviction
    snap = ring.snapshot()
    assert [e['event'] for e in snap] == ['breaker_transition',
                                          'chaos_injection',
                                          'chaos_injection']
    assert snap[0]['seq'] == 6 and snap[0]['source'] == 'router'
    assert snap[0]['url'] == 'http://r1'
    assert len(ring.snapshot(limit=1)) == 1
    c = reg.get('skytpu_events_total')
    assert c.value_for(kind='chaos_injection') == 5.0
    assert c.value_for(kind='breaker_transition') == 1.0


def test_chaos_injections_fan_out_to_event_sinks():
    from skypilot_tpu.observability import events as events_lib
    from skypilot_tpu.utils import chaos
    ring = events_lib.EventRing(source='test')

    def sink(point):
        ring.record('chaos_injection', point=point)

    chaos.add_event_sink(sink)
    chaos.add_event_sink(sink)             # idempotent registration
    try:
        chaos.configure('step_raise:p=1,n=1')
        assert chaos.should_inject('step_raise')
        events = [e for e in ring.snapshot()
                  if e['event'] == 'chaos_injection']
        assert len(events) == 1            # one sink entry => one event
        assert events[0]['point'] == 'step_raise'
    finally:
        chaos.disable()
        chaos._event_sinks.remove(sink)


# ---------------------------------------------------------------------
# Engine lifecycle accounting (real tiny paged engine)
# ---------------------------------------------------------------------

_OVERRIDES = dict(n_heads=4, n_kv_heads=2, max_seq_len=64, n_layers=2,
                  dim=64, ffn_dim=128, vocab_size=512,
                  param_dtype='float32', dtype='float32')


@pytest.fixture(scope='module')
def paged_engine():
    from skypilot_tpu.infer import engine as engine_lib
    reg = metrics_lib.Registry()
    eng = engine_lib.ContinuousBatchingEngine(
        'llama-tiny', n_slots=2, model_overrides=dict(_OVERRIDES),
        page_size=8, registry=reg)
    return eng, reg


def _vals(reg, *names):
    return [reg.get(n).value for n in names]


def test_engine_finished_requests_feed_metrics_and_traces(
        paged_engine):
    from skypilot_tpu.infer import engine as engine_lib
    eng, reg = paged_engine
    before_fin = reg.get('skytpu_requests_finished_total').value
    before_ttft = reg.get('skytpu_request_ttft_seconds').count
    cfg = engine_lib.SamplingConfig(max_new_tokens=3, temperature=0.0)
    prompt = list(range(1, 20))
    eng.generate([prompt], cfg)       # seed the prefix cache
    outs = eng.generate([prompt, prompt], cfg)
    assert all(len(o) == 3 for o in outs)
    fin, hits, misses = _vals(
        reg, 'skytpu_requests_finished_total',
        'skytpu_prefix_cache_page_hits_total',
        'skytpu_prefix_cache_page_misses_total')
    assert fin - before_fin == 3
    assert misses > 0
    assert hits >= 1          # re-prefill of a cached prompt hits
    assert reg.get('skytpu_request_ttft_seconds').count \
        - before_ttft == 3
    assert reg.get('skytpu_decode_cache_read_bytes').sum > 0
    assert reg.get('skytpu_kv_free_pages').value > 0
    # No leaked in-flight state once everything drained.
    assert reg.get('skytpu_requests_in_flight').value == 0
    assert eng.traces.inflight_count == 0
    done = [t for t in eng.traces.recent()
            if t['state'] == 'finished']
    assert len(done) >= 2
    assert done[0]['ttft_seconds'] is not None
    assert done[0]['output_tokens'] == 3


def test_engine_cancel_before_admission_counts_cancelled(
        paged_engine):
    from skypilot_tpu.infer import engine as engine_lib
    eng, reg = paged_engine
    before = reg.get('skytpu_requests_cancelled_total').value
    cfg = engine_lib.SamplingConfig(max_new_tokens=4)
    rid = eng.submit([1, 2, 3], cfg)
    eng.cancel(rid)                        # still queued: no step ran
    assert reg.get('skytpu_requests_cancelled_total').value \
        - before == 1
    assert eng.traces.get(rid).state == 'cancelled'
    assert eng.traces.inflight_count == 0
    assert reg.get('skytpu_requests_in_flight').value == 0


def test_engine_cancel_in_slot_counts_evicted(paged_engine):
    from skypilot_tpu.infer import engine as engine_lib
    eng, reg = paged_engine
    before = reg.get('skytpu_requests_evicted_total').value
    cfg = engine_lib.SamplingConfig(max_new_tokens=30,
                                    temperature=0.0)
    rid = eng.submit(list(range(1, 10)), cfg)
    for _ in range(4):                     # admit + a few decode steps
        eng.step()
    eng.cancel(rid)                        # slot-resident now
    eng.run_until_idle()                   # next tick evicts
    assert reg.get('skytpu_requests_evicted_total').value \
        - before == 1
    assert eng.traces.get(rid).state == 'evicted'
    assert eng.traces.inflight_count == 0
    assert reg.get('skytpu_requests_in_flight').value == 0


def test_engine_abort_counts_aborted():
    from skypilot_tpu.infer import engine as engine_lib
    reg = metrics_lib.Registry()
    eng = engine_lib.ContinuousBatchingEngine(
        'llama-tiny', n_slots=2, model_overrides=dict(_OVERRIDES),
        page_size=8, registry=reg)
    cfg = engine_lib.SamplingConfig(max_new_tokens=30)
    eng.submit(list(range(1, 10)), cfg)
    eng.submit(list(range(1, 6)), cfg)
    eng.abort(RuntimeError('device wedged'))
    assert reg.get('skytpu_requests_aborted_total').value == 2
    assert eng.traces.inflight_count == 0
    assert reg.get('skytpu_requests_in_flight').value == 0
    assert all(t['state'] == 'aborted' for t in eng.traces.recent())


def test_whole_batch_engine_counts(paged_engine):
    """InferenceEngine.generate (request-level API) shares the same
    metric names and trace derivations."""
    from skypilot_tpu.infer import engine as engine_lib
    reg = metrics_lib.Registry()
    eng = engine_lib.InferenceEngine(
        'llama-tiny', max_batch_size=2,
        model_overrides=dict(_OVERRIDES), registry=reg)
    cfg = engine_lib.SamplingConfig(max_new_tokens=3, temperature=0.0)
    outs = eng.generate([[1, 2, 3], [4, 5]], cfg)
    assert all(len(o) == 3 for o in outs)
    assert reg.get('skytpu_requests_finished_total').value == 2
    assert reg.get('skytpu_decode_steps_total').value == 3
    assert reg.get('skytpu_prompt_tokens_total').value == 5
    assert reg.get('skytpu_request_ttft_seconds').count == 2
    assert eng.traces.inflight_count == 0


# ---------------------------------------------------------------------
# Metric name contract + overhead guard (tier-1 acceptance)
# ---------------------------------------------------------------------

def test_every_registered_metric_name_matches_contract(paged_engine):
    """Single-sourced: the regex and allowed-name set both come from
    skypilot_tpu.observability (METRIC_NAME_RE / METRIC_CONTRACT),
    which the skylint metric-contract rule enforces statically."""
    from skypilot_tpu import observability
    from skypilot_tpu.infer import server as server_lib
    from skypilot_tpu.infer import speculative as speculative_lib
    from skypilot_tpu.observability import events as events_lib
    from skypilot_tpu.serve import replica_supervisor
    from skypilot_tpu.serve import router as router_lib
    from skypilot_tpu.train import trainer as trainer_lib
    _, reg = paged_engine
    server_lib._http_metrics(reg)
    trainer_lib._train_metrics(reg)
    router_lib._router_metrics(reg)
    replica_supervisor._supervisor_metrics(reg)
    speculative_lib.spec_metrics(reg)
    events_lib.EventRing(registry=reg)
    names = reg.names()
    assert len(names) >= 30
    for name in names:
        assert observability.METRIC_NAME_RE.fullmatch(name), name
        assert name in observability.METRIC_CONTRACT, name
    # Unit suffixes are not just permitted, they are used correctly
    # (_tokens: count-valued histograms, e.g. accepted spec length):
    for name in names:
        m = reg.get(name)
        if isinstance(m, metrics_lib.Counter):
            assert name.endswith('_total'), name
        if isinstance(m, metrics_lib.Histogram):
            assert name.endswith(('_seconds', '_bytes', '_tokens')), \
                name


def test_per_step_publish_overhead_under_two_percent(paged_engine):
    """The entire per-step telemetry cost is _publish_step_metrics;
    microbench it against a measured decode step (the bench's
    telemetry.publish_pct_of_step is the same contract, asserted on
    the real three-arm run by test_decode_smoke_paged_arm_end_to_end)."""
    import time

    from skypilot_tpu.infer import engine as engine_lib
    eng, _ = paged_engine
    cfg = engine_lib.SamplingConfig(max_new_tokens=16,
                                    temperature=0.0)
    eng.generate([[1, 2, 3], [4, 5, 6]], cfg)      # warm compiles
    t0 = time.perf_counter()
    eng.generate([[1, 2, 3], [4, 5, 6]], cfg)
    step_s = (time.perf_counter() - t0) / 16
    iters = 1000
    t0 = time.perf_counter()
    for _ in range(iters):
        # Full runtime-telemetry surface: occupancy + KV reads + the
        # host-step breakdown (dispatch vs device wait vs the host
        # work the async pipeline hid behind the step).
        eng._publish_step_metrics(2, 1e6, dispatch_s=0.004,
                                  device_wait_s=0.001,
                                  host_overlap_s=0.002)
    publish_s = (time.perf_counter() - t0) / iters
    assert publish_s < 0.02 * step_s, (
        f'publish {publish_s * 1e6:.1f}us vs step '
        f'{step_s * 1e3:.2f}ms')


def test_publish_books_host_overlap_only_when_measured(paged_engine):
    """The overlap histogram is the async pipeline's accounting: a
    synchronous tick (host_overlap_s=None) must not record a sample,
    an async tick records exactly its measured overlap — 0.0 included
    (an empty-overlap tick is a fact, not a gap)."""
    eng, reg = paged_engine
    h = reg.get('skytpu_step_host_overlap_seconds')
    c0, s0 = h.count, h.sum
    eng._publish_step_metrics(1, 0.0, device_wait_s=0.001)
    assert (h.count, h.sum) == (c0, s0)     # sync tick: no sample
    eng._publish_step_metrics(1, 0.0, host_overlap_s=0.25)
    assert h.count == c0 + 1
    assert h.sum == pytest.approx(s0 + 0.25)
    eng._publish_step_metrics(1, 0.0, host_overlap_s=0.0)
    assert h.count == c0 + 2
    assert h.sum == pytest.approx(s0 + 0.25)


# Test surfaces this PR added: scanned by the tier-1 guard below.
_PR_TEST_SURFACES = {
    'test_observability.py': None,       # whole file
    'test_server_metrics.py': None,      # whole file
    'test_bench_capture.py': ['test_decode_emits_one_json_line'],
}


class TestTier1Guard:
    """Every test this PR added must run in the tier-1 lane: CPU
    backend, no `slow` marker, no TPU gating — the telemetry and
    overhead contracts are only contracts if CI executes them."""

    def test_runs_on_cpu_backend(self):
        assert jax.default_backend() == 'cpu'

    def test_new_tests_not_slow_marked(self):
        import pathlib
        here = pathlib.Path(__file__).parent
        for fname, surfaces in _PR_TEST_SURFACES.items():
            text = (here / fname).read_text()
            if surfaces is None:
                scopes = [text]
            else:
                scopes = []
                for name in surfaces:
                    assert name in text, (fname, name)
                    scopes.append(text[text.index(name):
                                       text.index(name) + 4000])
            # Needles assembled at runtime so the guard's own source
            # (scanned as part of this file) never matches itself.
            slow, tpu = 'mark.' + 'slow', 'requires' + '_tpu'
            for scope in scopes:
                assert slow not in scope, fname
                assert tpu not in scope, fname
