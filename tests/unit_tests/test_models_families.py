"""Gemma + GPT-2 family tests: forward shapes, architectural deltas
(tied heads, GeGLU, plus-one norms, learned positions), causality,
trainer integration on the 8-device mesh, registry dispatch."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu import models
from skypilot_tpu.models import gemma
from skypilot_tpu.models import gpt2
from skypilot_tpu.parallel import sharding as sharding_lib


def _count(tree):
    return sum(x.size for x in jax.tree.leaves(tree))


class TestGemma:

    def test_forward_shape_and_registry(self):
        model, cfg = models.get_model('gemma-tiny', remat=False)
        tokens = jnp.zeros((2, 32), jnp.int32)
        variables = model.init(jax.random.PRNGKey(0), tokens)
        logits = model.apply(variables, tokens)
        assert logits.shape == (2, 32, cfg.vocab_size)
        assert logits.dtype == jnp.float32

    def test_tied_head_no_lm_head_params(self):
        model, cfg = models.get_model('gemma-tiny', remat=False)
        variables = model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 8), jnp.int32))
        params = sharding_lib.unbox(variables['params'])
        assert 'lm_head' not in params  # tied to tok_embed
        assert _count(params) == gemma.num_params(cfg)

    def test_plus_one_norm_and_geglu_in_effect(self):
        """At init the RMSNorm offset param is all zeros (scale==1
        effective); the MLP must be GeGLU (gelu-gated)."""
        model, cfg = models.get_model('gemma-tiny', remat=False,
                                      scan_layers=False)
        variables = model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 8), jnp.int32))
        params = sharding_lib.unbox(variables['params'])
        scale = params['layer_0']['attention_norm']['scale']
        np.testing.assert_array_equal(np.asarray(scale), 0.0)
        assert cfg.activation == 'gelu' and cfg.norm_plus_one

    def test_embed_scaling_changes_output(self):
        """sqrt(dim) embedding scaling is load-bearing: a no-scale
        forward differs."""
        cfg = gemma.get_config('gemma-tiny', remat=False,
                               dtype=jnp.float32)
        model = gemma.Gemma(cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0,
                                    cfg.vocab_size)
        variables = model.init(jax.random.PRNGKey(0), tokens)
        out = model.apply(variables, tokens)
        assert jnp.isfinite(out).all()
        # Scaled embeddings at init have RMS ≈ 1 (normal(1.0) * sqrt(d)
        # / sqrt(d) ... sanity: outputs are in a sane range, not 1e-2).
        assert jnp.abs(out).max() > 1e-2

    def test_causality(self):
        cfg = gemma.get_config('gemma-tiny', remat=False,
                               dtype=jnp.float32)
        model = gemma.Gemma(cfg)
        t1 = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0,
                                cfg.vocab_size)
        t2 = t1.at[0, -1].set((t1[0, -1] + 1) % cfg.vocab_size)
        variables = model.init(jax.random.PRNGKey(0), t1)
        o1 = model.apply(variables, t1)
        o2 = model.apply(variables, t2)
        np.testing.assert_allclose(o1[0, :-1], o2[0, :-1], atol=1e-5)

    def test_logit_softcap(self):
        cfg = gemma.get_config('gemma-tiny', remat=False,
                               final_logit_softcap=5.0)
        model = gemma.Gemma(cfg)
        tokens = jnp.zeros((1, 8), jnp.int32)
        variables = model.init(jax.random.PRNGKey(0), tokens)
        logits = model.apply(variables, tokens)
        assert jnp.abs(logits).max() <= 5.0

    def test_decode_cache_matches_full_forward(self):
        """Token-by-token decode through the shared KV cache must match
        the full (non-decode) forward."""
        cfg_full = gemma.get_config('gemma-tiny', remat=False,
                                    dtype=jnp.float32,
                                    attention_impl='reference')
        cfg_dec = gemma.get_config('gemma-tiny', remat=False,
                                   dtype=jnp.float32, decode=True,
                                   max_seq_len=16)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0,
                                    cfg_full.vocab_size)
        m_full = gemma.Gemma(cfg_full)
        variables = m_full.init(jax.random.PRNGKey(0), tokens)
        full_logits = m_full.apply(variables, tokens)

        m_dec = gemma.Gemma(cfg_dec)
        # init() runs the module body (cursor advances past the dummy
        # token): start decoding from a pristine zero cache, as the
        # inference engine does (infer/engine.py eval_shape + zeros).
        cache = jax.tree.map(
            jnp.zeros_like,
            m_dec.init(jax.random.PRNGKey(0),
                       jnp.zeros((1, 1), jnp.int32))['cache'])
        step_logits = []
        for i in range(tokens.shape[1]):
            out, mut = m_dec.apply(
                {'params': variables['params'], 'cache': cache},
                tokens[:, i:i + 1],
                jnp.full((1, 1), i, jnp.int32),
                mutable=['cache'])
            cache = mut['cache']
            step_logits.append(out[:, 0])
        np.testing.assert_allclose(
            jnp.stack(step_logits, axis=1), full_logits,
            atol=2e-3, rtol=2e-3)


class TestGpt2:

    def test_forward_shape_and_registry(self):
        model, cfg = models.get_model('gpt2-tiny', remat=False)
        tokens = jnp.zeros((2, 32), jnp.int32)
        variables = model.init(jax.random.PRNGKey(0), tokens)
        logits = model.apply(variables, tokens)
        assert logits.shape == (2, 32, cfg.vocab_size)

    def test_param_count_and_tied_head(self):
        model, cfg = models.get_model('gpt2-tiny', remat=False)
        variables = model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 8), jnp.int32))
        params = sharding_lib.unbox(variables['params'])
        assert 'lm_head' not in params
        assert _count(params) == gpt2.num_params(cfg)

    def test_positions_are_learned_not_rotary(self):
        """Same tokens at different positions must produce different
        logits (learned absolute positions)."""
        cfg = gpt2.get_config('gpt2-tiny', remat=False,
                              dtype=jnp.float32)
        model = gpt2.Gpt2(cfg)
        tokens = jnp.full((1, 4), 7, jnp.int32)
        variables = model.init(jax.random.PRNGKey(0), tokens)
        p0 = model.apply(variables, tokens,
                         jnp.arange(4, dtype=jnp.int32)[None])
        p5 = model.apply(variables, tokens,
                         (jnp.arange(4, dtype=jnp.int32) + 5)[None])
        assert not np.allclose(np.asarray(p0), np.asarray(p5))
        params = sharding_lib.unbox(variables['params'])
        assert 'pos_embed' in params

    def test_causality(self):
        cfg = gpt2.get_config('gpt2-tiny', remat=False,
                              dtype=jnp.float32)
        model = gpt2.Gpt2(cfg)
        t1 = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0,
                                cfg.vocab_size)
        t2 = t1.at[0, -1].set((t1[0, -1] + 1) % cfg.vocab_size)
        variables = model.init(jax.random.PRNGKey(0), t1)
        o1 = model.apply(variables, t1)
        o2 = model.apply(variables, t2)
        np.testing.assert_allclose(o1[0, :-1], o2[0, :-1], atol=1e-5)

    def test_gpt2_full_size_param_count(self):
        # The canonical GPT-2 small is ~124M params.
        assert 123e6 < gpt2.num_params(gpt2.CONFIGS['gpt2']) < 126e6

    def test_decode_cache_matches_full_forward(self):
        """GPT-2 serves through the shared KV cache: token-by-token
        decode must match the full forward."""
        # Same max_seq_len in both: pos_embed is sized by it.
        cfg_full = gpt2.get_config('gpt2-tiny', remat=False,
                                   dtype=jnp.float32, max_seq_len=16,
                                   attention_impl='reference')
        cfg_dec = gpt2.get_config('gpt2-tiny', remat=False,
                                  dtype=jnp.float32, decode=True,
                                  max_seq_len=16)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0,
                                    cfg_full.vocab_size)
        m_full = gpt2.Gpt2(cfg_full)
        variables = m_full.init(jax.random.PRNGKey(0), tokens)
        full_logits = m_full.apply(variables, tokens)
        m_dec = gpt2.Gpt2(cfg_dec)
        cache = jax.tree.map(
            jnp.zeros_like,
            m_dec.init(jax.random.PRNGKey(0),
                       jnp.zeros((1, 1), jnp.int32))['cache'])
        step_logits = []
        for i in range(tokens.shape[1]):
            out, mut = m_dec.apply(
                {'params': variables['params'], 'cache': cache},
                tokens[:, i:i + 1],
                jnp.full((1, 1), i, jnp.int32),
                mutable=['cache'])
            cache = mut['cache']
            step_logits.append(out[:, 0])
        np.testing.assert_allclose(
            jnp.stack(step_logits, axis=1), full_logits,
            atol=2e-3, rtol=2e-3)


class TestTrainerIntegration:

    @pytest.mark.parametrize('name', ['gemma-tiny', 'gpt2-tiny'])
    def test_sharded_train_loss_decreases(self, name):
        """Both new families must train sharded (data x fsdp mesh) out
        of the box — logical axis names feed the same sharding rules."""
        from skypilot_tpu.parallel import mesh as mesh_lib
        from skypilot_tpu.train import data as data_lib
        from skypilot_tpu.train import trainer as trainer_lib
        config = trainer_lib.TrainConfig(
            model=name, global_batch_size=8, seq_len=32,
            total_steps=12, warmup_steps=1,
            mesh=mesh_lib.MeshConfig(data=2, fsdp=-1),
            model_overrides={'max_seq_len': 64})
        trainer = trainer_lib.Trainer(config)
        trainer.init_state()
        data_iter = data_lib.synthetic_data(
            trainer.mesh, global_batch_size=8, seq_len=32,
            vocab_size=trainer.model_config.vocab_size)
        batch = next(data_iter)
        first = last = None
        for _ in range(12):
            metrics = trainer.step(batch)
            loss = float(jax.device_get(metrics['loss']))
            first = first if first is not None else loss
            last = loss
        assert last < first, (first, last)


class TestQwen:

    def test_forward_shape_and_registry(self):
        model, cfg = models.get_model('qwen-tiny', remat=False)
        tokens = jnp.zeros((2, 32), jnp.int32)
        variables = model.init(jax.random.PRNGKey(0), tokens)
        logits = model.apply(variables, tokens)
        assert logits.shape == (2, 32, cfg.vocab_size)
        assert 'qwen2-7b' in models.available_models()

    def test_qkv_bias_present_o_bias_absent(self):
        """The Qwen2 signature: biases on Q/K/V only."""
        model, _ = models.get_model('qwen-tiny', remat=False,
                                    scan_layers=False)
        variables = model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 8), jnp.int32))
        params = sharding_lib.unbox(variables['params'])
        attn = params['layer_0']['attention']
        for proj in ('q_proj', 'k_proj', 'v_proj'):
            assert 'bias' in attn[proj], proj
        assert 'bias' not in attn['o_proj']

    def test_param_count_tied_and_untied(self):
        from skypilot_tpu.models import qwen
        for tie in (True, False):
            model, cfg = models.get_model('qwen-tiny', remat=False,
                                          tie_embeddings=tie)
            variables = model.init(jax.random.PRNGKey(0),
                                   jnp.zeros((1, 8), jnp.int32))
            params = sharding_lib.unbox(variables['params'])
            assert ('lm_head' in params) == (not tie)
            assert _count(params) == qwen.num_params(cfg), tie

    def test_decode_cache_matches_full_forward(self):
        from skypilot_tpu.models import qwen
        cfg_full = qwen.get_config('qwen-tiny', remat=False,
                                   dtype=jnp.float32,
                                   param_dtype=jnp.float32,
                                   attention_impl='reference')
        cfg_dec = qwen.get_config('qwen-tiny', remat=False,
                                  dtype=jnp.float32,
                                  param_dtype=jnp.float32,
                                  decode=True, max_seq_len=16)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0,
                                    cfg_full.vocab_size)
        m_full = qwen.Qwen(cfg_full)
        variables = m_full.init(jax.random.PRNGKey(0), tokens)
        full_logits = m_full.apply(variables, tokens)

        m_dec = qwen.Qwen(cfg_dec)
        cache = jax.tree.map(
            jnp.zeros_like,
            m_dec.init(jax.random.PRNGKey(0),
                       jnp.zeros((1, 1), jnp.int32))['cache'])
        step_logits = []
        for i in range(tokens.shape[1]):
            out, mut = m_dec.apply(
                {'params': variables['params'], 'cache': cache},
                tokens[:, i:i + 1],
                jnp.full((1, 1), i, jnp.int32),
                mutable=['cache'])
            cache = mut['cache']
            step_logits.append(out[:, 0])
        np.testing.assert_allclose(
            jnp.stack(step_logits, axis=1), full_logits,
            atol=2e-3, rtol=2e-3)

    def test_trainer_one_step_sharded(self):
        from skypilot_tpu.parallel import mesh as mesh_lib
        from skypilot_tpu.train import data as data_lib
        from skypilot_tpu.train import trainer as trainer_lib
        config = trainer_lib.TrainConfig(
            model='qwen-tiny', global_batch_size=8, seq_len=64,
            total_steps=1,
            mesh=mesh_lib.MeshConfig(data=2, fsdp=2, tensor=2),
            model_overrides={'max_seq_len': 64, 'remat': False})
        trainer = trainer_lib.Trainer(config)
        trainer.init_state()
        it = data_lib.synthetic_data(
            trainer.mesh, global_batch_size=8, seq_len=64,
            vocab_size=trainer.model_config.vocab_size)
        loss = float(jax.device_get(trainer.step(next(it))['loss']))
        assert loss > 0

    def test_continuous_batching_serves_qwen(self):
        from skypilot_tpu.infer import engine as engine_lib
        eng = engine_lib.ContinuousBatchingEngine(
            'qwen-tiny', n_slots=2,
            model_overrides={'dtype': jnp.float32,
                             'param_dtype': jnp.float32,
                             'max_seq_len': 64},
            param_dtype=jnp.float32, prefill_bucket=8)
        outs = eng.generate(
            [[1, 2, 3], [4, 5]],
            engine_lib.SamplingConfig(max_new_tokens=4))
        assert all(len(o) == 4 for o in outs)


class TestFamilyServingMatrix:
    """Every decoder family serves through the continuous-batching
    engine with cache-free-exact greedy decode (llama/mixtral/qwen are
    covered elsewhere; this locks in gemma + gpt2)."""

    @pytest.mark.parametrize('name,overrides', [
        ('gemma-tiny', {'max_seq_len': 64, 'dtype': jnp.float32,
                        'param_dtype': jnp.float32, 'remat': False}),
        ('gpt2-tiny', {'max_seq_len': 64, 'dtype': jnp.float32,
                       'param_dtype': jnp.float32, 'remat': False}),
    ])
    def test_continuous_engine_matches_cache_free(self, name,
                                                  overrides):
        from skypilot_tpu.infer import engine as engine_lib
        eng = engine_lib.ContinuousBatchingEngine(
            name, n_slots=2, model_overrides=dict(overrides),
            param_dtype=jnp.float32, prefill_bucket=8)
        prompt = [5, 17, 3, 9]
        got = eng.generate(
            [prompt], engine_lib.SamplingConfig(max_new_tokens=5))[0]

        model, _ = models.get_model(name, decode=False, **overrides)
        toks = list(prompt)
        want = []
        for _ in range(5):
            logits = model.apply({'params': eng.params},
                                 jnp.asarray([toks], jnp.int32))
            nxt = int(jnp.argmax(logits[0, -1]))
            want.append(nxt)
            toks.append(nxt)
        assert got == want, (name, got, want)
