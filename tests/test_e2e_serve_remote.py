"""End-to-end: self-hosted serve controller on a controller cluster.

Reference semantics (sky/serve/core.py:136 + sky-serve-controller
.yaml.j2): the service runtime (controller + autoscaler + LB) runs on
its own cluster, so serving survives the submitting client.  Exercised
hermetically: the controller cluster and every replica are local
process clusters; the runtime process is parented to the controller
cluster's detached agent, not to this test.
"""
import shlex
import time
import urllib.request

import pytest

import skypilot_tpu as sky
from skypilot_tpu.serve import remote as serve_remote

CONTROLLER = 'sc1'

_SERVER_PY = (
    "import os,sys;"
    "from http.server import BaseHTTPRequestHandler,HTTPServer\n"
    "class H(BaseHTTPRequestHandler):\n"
    "    def do_GET(self):\n"
    "        b=('replica-'+os.environ['SKYTPU_SERVE_REPLICA_ID'])"
    ".encode()\n"
    "        self.send_response(200);"
    "self.send_header('Content-Length',str(len(b)));"
    "self.end_headers();self.wfile.write(b)\n"
    "    def log_message(self,*a): pass\n"
    "HTTPServer(('127.0.0.1',int(os.environ["
    "'SKYTPU_SERVE_REPLICA_PORT'])),H).serve_forever()\n")


@pytest.fixture(autouse=True)
def _fast_runtime(monkeypatch):
    """The detached service runtime inherits env through the agent
    chain (same route SKYTPU_STATE_DIR takes); production control-loop
    intervals (10-20s) would make this test wait out several cycles."""
    monkeypatch.setenv('SKYTPU_SERVE_AUTOSCALER_INTERVAL_SECONDS', '0.3')
    monkeypatch.setenv('SKYTPU_SERVE_PROBE_INTERVAL_SECONDS', '0.3')
    monkeypatch.setenv('SKYTPU_SERVE_LB_SYNC_INTERVAL_SECONDS', '0.4')
    yield


@pytest.fixture(autouse=True)
def _teardown():
    yield
    try:
        serve_remote.down(all_services=True,
                          controller_cluster=CONTROLLER)
    except Exception:  # noqa: BLE001
        pass
    try:
        sky.down(CONTROLLER)
    except Exception:  # noqa: BLE001
        pass


def _service_task():
    t = sky.Task(run=f'python3 -c {shlex.quote(_SERVER_PY)}')
    t.set_resources(sky.Resources(cloud='local'))
    from skypilot_tpu.serve import service_spec as spec_lib
    t.set_service(spec_lib.SkyServiceSpec(
        readiness_path='/health', initial_delay_seconds=60,
        readiness_timeout_seconds=2, min_replicas=1))
    return t


def _wait(pred, timeout, desc):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.5)
    raise TimeoutError(f'timed out waiting for {desc}')


class TestServeRemoteController:

    def test_remote_up_serves_traffic_and_downs(self):
        result = serve_remote.up(
            _service_task(), service_name='rsvc',
            controller_cluster=CONTROLLER,
            resources=sky.Resources(cloud='local'))
        assert result['controller_cluster'] == CONTROLLER
        endpoint = result['endpoint']
        assert endpoint.startswith('http://')

        # Status through the controller-head RPC path.
        def _ready():
            services = serve_remote.status(
                ['rsvc'], controller_cluster=CONTROLLER)
            if not services:
                return False
            replicas = services[0].get('replica_info', [])
            return any(str(r.get('status')) == 'READY'
                       for r in replicas)

        _wait(_ready, 120, 'remote service READY')

        # Real traffic through the controller-hosted load balancer.
        body = None
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(endpoint + '/x',
                                            timeout=5) as r:
                    body = r.read().decode()
                break
            except Exception:  # noqa: BLE001 — LB may still be binding
                time.sleep(0.5)
        assert body and body.startswith('replica-'), body

        # Rolling update through the controller: bump the spec/task.
        version = serve_remote.update(_service_task(), 'rsvc',
                                      controller_cluster=CONTROLLER)
        assert version == 2

        downed = serve_remote.down(['rsvc'],
                                   controller_cluster=CONTROLLER)
        assert downed == ['rsvc']
        _wait(lambda: not serve_remote.status(
            ['rsvc'], controller_cluster=CONTROLLER)
            or str(serve_remote.status(
                ['rsvc'],
                controller_cluster=CONTROLLER)[0].get('status'))
            in ('SHUTDOWN', 'SHUTTING_DOWN', 'FAILED'),
            60, 'service torn down')
