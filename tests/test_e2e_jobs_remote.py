"""End-to-end: self-hosted jobs controller on a controller cluster.

The reference's marquee managed-jobs property — recovery survives the
client because the controller runs on its own cluster
(sky/jobs/core.py:39 + jobs-controller.yaml.j2) — exercised hermetically:
the controller cluster and the task cluster are both local process
clusters; preemption is injected by terminating the task cluster's
instances through the provisioner API.  The controller process is
parented to the (detached) agent daemon of the controller cluster, not
to this test process, which is the survives-client-exit property.
"""
import os
import time

import psutil
import pytest

import skypilot_tpu as sky
from skypilot_tpu import global_user_state
from skypilot_tpu.jobs import remote as jobs_remote
from skypilot_tpu.jobs import state as jobs_state
from skypilot_tpu.provision.local import instance as local_instance

CONTROLLER = 'jc1'


@pytest.fixture(autouse=True)
def _fast_loops(monkeypatch):
    monkeypatch.setenv('SKYTPU_JOBS_STATUS_GAP', '0.3')
    monkeypatch.setenv('SKYTPU_JOBS_LAUNCH_BACKOFF', '0.2')
    yield
    # Tearing down the controller cluster kills the controller process
    # tree (local provisioner reaper), so nothing leaks into later tests.
    try:
        sky.down(CONTROLLER)
    except Exception:  # noqa: BLE001
        pass


def _wait(predicate, timeout, desc):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return
        time.sleep(0.25)
    raise TimeoutError(f'timed out waiting for {desc}')


def _newest_job():
    rows = jobs_state.get_managed_jobs()
    return rows[0] if rows else None


def _task_row(job_id):
    return jobs_state.get_job_tasks(job_id)[0]


class TestSelfHostedController:

    def test_recovers_after_preemption_without_client(self):
        task = sky.Task(run='sleep 600', name='rmj')
        task.set_resources(sky.Resources(cloud='local'))
        cluster, agent_job = jobs_remote.launch(
            task, controller_cluster=CONTROLLER,
            resources=sky.Resources(cloud='local'))
        assert cluster == CONTROLLER

        # The controller host shares this machine's state dir (local
        # cloud), so the managed-job rows become visible here once the
        # controller-side registration runs.
        _wait(lambda: _newest_job() is not None, 60, 'job registered')
        job_id = _newest_job()['job_id']
        _wait(lambda: _task_row(job_id)['status'] ==
              jobs_state.ManagedJobStatus.RUNNING, 90, 'RUNNING')

        # The recovery loop must not live in this (client) process: no
        # controller threads here, and the controller process is in a
        # different session (parented to the detached agent daemon).
        from skypilot_tpu.jobs import controller as controller_lib
        assert not [t for t in controller_lib._ACTIVE_THREADS  # pylint: disable=protected-access
                    if t.is_alive()]
        my_sid = os.getsid(os.getpid())
        controller_procs = []
        for proc in psutil.process_iter(['pid', 'cmdline']):
            try:
                cmd = ' '.join(proc.info['cmdline'] or [])
                if 'skypilot_tpu.jobs.remote' in cmd and '--dag' in cmd:
                    controller_procs.append(proc)
            except (psutil.NoSuchProcess, psutil.AccessDenied):
                continue
        assert controller_procs, 'controller process not found'
        assert all(os.getsid(p.pid) != my_sid for p in controller_procs), \
            'controller runs in the client session'

        # Preempt the task cluster out from under the remote controller.
        task_cluster = _task_row(job_id)['cluster_name']
        record = global_user_state.get_cluster_from_name(task_cluster)
        assert record is not None
        local_instance.terminate_instances(
            record['handle'].cluster_name_on_cloud)

        _wait(lambda: _task_row(job_id)['recovery_count'] >= 1, 120,
              'recovery')
        _wait(lambda: _task_row(job_id)['status'] ==
              jobs_state.ManagedJobStatus.RUNNING, 90,
              'RUNNING after recovery')

        # Client-side RPC surface against the controller cluster.
        queue = jobs_remote.queue(controller_cluster=CONTROLLER)
        assert any(j['job_id'] == job_id for j in queue)
        log = jobs_remote.tail_logs(job_id,
                                    controller_cluster=CONTROLLER)
        # Controller event log: registration/launch events present.
        assert '"event"' in log and 'submitted' in log, log[-300:]
        cancelled = jobs_remote.cancel(job_ids=[job_id],
                                       controller_cluster=CONTROLLER)
        assert cancelled == [job_id]
        _wait(lambda: jobs_state.get_status(job_id) ==
              jobs_state.ManagedJobStatus.CANCELLED, 90, 'CANCELLED')

        # The managed task cluster is gone; the agent job on the
        # controller cluster reaches a terminal state.
        _wait(lambda: global_user_state.get_cluster_from_name(
            task_cluster) is None, 60, 'task cluster torn down')
        _wait(lambda: sky.job_status(CONTROLLER, [agent_job])[agent_job]
              in ('SUCCEEDED', 'FAILED'), 60, 'controller job finished')
