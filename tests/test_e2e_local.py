"""End-to-end lifecycle tests on process-based local clusters.

The hermetic analog of the reference's smoke tests
(tests/smoke_tests/test_cluster_job.py etc., which need real clouds):
launch → gang exec → logs → queue → cancel → exec fast path → down, all
real processes, no cloud.
"""
import io
import os
import time

import pytest

import skypilot_tpu as sky
from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state


def _wait_job(cluster, job_id, timeout=30):
    deadline = time.time() + timeout
    while time.time() < deadline:
        status = sky.job_status(cluster, [job_id])[job_id]
        if status in ('SUCCEEDED', 'FAILED', 'FAILED_DRIVER', 'CANCELLED'):
            return status
        time.sleep(0.25)
    raise TimeoutError(f'job {job_id} still {status}')


def _local_task(run, num_nodes=1, accelerators=None, **kwargs):
    t = sky.Task(run=run, num_nodes=num_nodes, **kwargs)
    t.set_resources(sky.Resources(cloud='local',
                                  accelerators=accelerators))
    return t


def _read_run_log(cluster, job_id):
    record = global_user_state.get_cluster_from_name(cluster)
    root = record['handle'].head_agent_root
    path = os.path.join(root, '.skytpu_agent', 'job_logs', f'job_{job_id}',
                        'run.log')
    with open(path, encoding='utf-8') as f:
        return f.read()


class TestEndToEnd:

    def test_launch_and_logs(self):
        t = _local_task('echo "rank $SKYTPU_NODE_RANK of $SKYTPU_NUM_NODES"')
        job_id, handle = sky.launch(t, cluster_name='t1',
                                    quiet_optimizer=True, detach_run=True)
        assert _wait_job('t1', job_id) == 'SUCCEEDED'
        log = _read_run_log('t1', job_id)
        assert 'rank 0 of 1' in log
        records = sky.status(['t1'])
        assert records[0]['status'] == sky.ClusterStatus.UP
        sky.down('t1')
        assert sky.status(['t1']) == []

    def test_slice_gang_ranks(self):
        """A tpu-v5e-16 'slice' = 4 hosts; one process per host with the
        full rank/coordinator env contract."""
        t = _local_task(
            'echo "r=$SKYTPU_NODE_RANK n=$SKYTPU_NUM_NODES '
            'pid=$SKYTPU_PROCESS_ID np=$SKYTPU_NUM_PROCESSES '
            'coord=$SKYTPU_COORDINATOR_ADDR acc=$SKYTPU_ACCELERATOR"',
            accelerators='tpu-v5e-16')
        job_id, _ = sky.launch(t, cluster_name='t2', quiet_optimizer=True,
                               detach_run=True)
        assert _wait_job('t2', job_id) == 'SUCCEEDED'
        log = _read_run_log('t2', job_id)
        for rank in range(4):
            assert f'r={rank} n=4 pid={rank} np=4' in log
        assert 'acc=tpu-v5e-16' in log
        assert ':8476' in log
        sky.down('t2')

    def test_multislice_megascale_env(self):
        """num_nodes=2 TPU slices = a multislice job: each rank gets the
        MEGASCALE DCN contract (coordinator on the dedicated port, slice
        ids by logical node) on top of the rank/coordinator env."""
        t = _local_task(
            'echo "r=$SKYTPU_NODE_RANK slice=$MEGASCALE_SLICE_ID '
            'n=$MEGASCALE_NUM_SLICES coord=$MEGASCALE_COORDINATOR_ADDRESS"',
            num_nodes=2, accelerators='tpu-v5e-8')
        job_id, _ = sky.launch(t, cluster_name='tms', quiet_optimizer=True,
                               detach_run=True)
        assert _wait_job('tms', job_id) == 'SUCCEEDED'
        log = _read_run_log('tms', job_id)
        # tpu-v5e-8 = 2 hosts/slice: ranks 0-1 are slice 0, 2-3 slice 1.
        assert 'r=0 slice=0 n=2' in log
        assert 'r=1 slice=0 n=2' in log
        assert 'r=2 slice=1 n=2' in log
        assert 'r=3 slice=1 n=2' in log
        assert ':8477' in log
        assert ':8080' not in log
        sky.down('tms')

    def test_gang_failure_cancels_peers(self):
        """Reference get_or_fail semantics (cloud_vm_ray_backend.py:313):
        one rank failing kills the others."""
        t = _local_task(
            'if [ "$SKYTPU_NODE_RANK" = "1" ]; then exit 7; fi; sleep 60',
            num_nodes=3)
        job_id, _ = sky.launch(t, cluster_name='t3', quiet_optimizer=True,
                               detach_run=True)
        start = time.time()
        assert _wait_job('t3', job_id, timeout=30) == 'FAILED'
        assert time.time() - start < 25, 'peers not cancelled promptly'
        log = _read_run_log('t3', job_id)
        assert 'rank 1 failed' in log
        sky.down('t3')

    def test_exec_fast_path_and_queue(self):
        t = _local_task('echo first')
        job1, _ = sky.launch(t, cluster_name='t4', quiet_optimizer=True,
                             detach_run=True)
        assert _wait_job('t4', job1) == 'SUCCEEDED'
        t2 = _local_task('echo second')
        job2, _ = sky.exec(t2, 't4', detach_run=True)
        assert job2 == job1 + 1
        assert _wait_job('t4', job2) == 'SUCCEEDED'
        queue = sky.queue('t4')
        assert [j['job_id'] for j in queue] == [job2, job1]
        assert all(j['status'] == 'SUCCEEDED' for j in queue)
        sky.down('t4')

    def test_exec_on_missing_cluster(self):
        with pytest.raises(exceptions.ClusterDoesNotExist):
            sky.exec(_local_task('echo x'), 'nonexistent-cluster')

    def test_cancel_running_job(self):
        t = _local_task('sleep 120')
        job_id, _ = sky.launch(t, cluster_name='t5', quiet_optimizer=True,
                               detach_run=True)
        deadline = time.time() + 20
        while time.time() < deadline:
            if sky.job_status('t5', [job_id])[job_id] == 'RUNNING':
                break
            time.sleep(0.25)
        cancelled = sky.cancel('t5', [job_id])
        assert cancelled == [job_id]
        assert _wait_job('t5', job_id) == 'CANCELLED'
        # The rank process (sleep 120, own session) must actually be dead —
        # the driver's SIGTERM handler reaps it (not just the driver).
        record = global_user_state.get_cluster_from_name('t5')
        root = record['handle'].head_agent_root
        import psutil
        deadline = time.time() + 10
        while time.time() < deadline:
            leftovers = []
            for proc in psutil.process_iter(['pid', 'environ']):
                try:
                    env = proc.info['environ'] or {}
                    if env.get('SKYTPU_JOB_ID') == str(job_id) and \
                            env.get('SKYTPU_LOCAL_HOST_ROOT', '').startswith(
                                os.path.dirname(os.path.dirname(root))):
                        leftovers.append(proc.pid)
                except (psutil.NoSuchProcess, psutil.AccessDenied):
                    continue
            if not leftovers:
                break
            time.sleep(0.5)
        assert not leftovers, f'rank processes leaked: {leftovers}'
        sky.down('t5')

    def test_workdir_and_file_mounts(self, tmp_path):
        workdir = tmp_path / 'proj'
        workdir.mkdir()
        (workdir / 'data.txt').write_text('payload42')
        extra = tmp_path / 'extra.txt'
        extra.write_text('mounted')
        t = _local_task('cat data.txt && cat ../extra_mount/extra.txt',
                        workdir=str(workdir))
        t.set_file_mounts({'extra_mount/extra.txt': str(extra)})
        job_id, _ = sky.launch(t, cluster_name='t6', quiet_optimizer=True,
                               detach_run=True)
        assert _wait_job('t6', job_id) == 'SUCCEEDED'
        log = _read_run_log('t6', job_id)
        assert 'payload42' in log
        assert 'mounted' in log
        sky.down('t6')

    def test_setup_runs_before_job(self):
        t = _local_task('cat marker.txt')
        t.setup = 'echo from-setup > marker.txt'
        job_id, _ = sky.launch(t, cluster_name='t7', quiet_optimizer=True,
                               detach_run=True)
        assert _wait_job('t7', job_id) == 'SUCCEEDED'
        assert 'from-setup' in _read_run_log('t7', job_id)
        sky.down('t7')

    def test_setup_failure_raises(self):
        t = _local_task('echo never')
        t.setup = 'exit 3'
        with pytest.raises(exceptions.CommandError):
            sky.launch(t, cluster_name='t8', quiet_optimizer=True,
                       detach_run=True)
        sky.down('t8')

    def test_callable_run(self):
        def run_fn(rank, ips):
            return f'echo "generated for rank {rank}/{len(ips)}"'

        t = _local_task(run_fn, num_nodes=2)
        job_id, _ = sky.launch(t, cluster_name='t9', quiet_optimizer=True,
                               detach_run=True)
        assert _wait_job('t9', job_id) == 'SUCCEEDED'
        log = _read_run_log('t9', job_id)
        assert 'generated for rank 0/2' in log
        assert 'generated for rank 1/2' in log
        sky.down('t9')

    def test_cost_report_after_down(self):
        t = _local_task('echo x')
        job_id, _ = sky.launch(t, cluster_name='t10', quiet_optimizer=True,
                               detach_run=True)
        _wait_job('t10', job_id)
        sky.down('t10')
        report = sky.cost_report()
        mine = [r for r in report if r['name'] == 't10']
        assert len(mine) == 1
        assert not mine[0]['still_exists']
        assert mine[0]['duration_seconds'] >= 0

    def test_agent_restarts_on_version_change(self):
        """Reference attempt_skylet semantics: a launch onto an UP
        cluster whose agent predates the shipped runtime restarts the
        agent; a matching agent is left alone."""
        t = _local_task('echo x')
        job_id, _ = sky.launch(t, cluster_name='tvg', quiet_optimizer=True,
                               detach_run=True)
        _wait_job('tvg', job_id)
        record = global_user_state.get_cluster_from_name('tvg')
        root = record['handle'].head_agent_root
        agent_dir = os.path.join(root, '.skytpu_agent')
        pid_file = os.path.join(agent_dir, 'agent.pid')
        with open(pid_file, encoding='utf-8') as f:
            pid1 = int(f.read())

        # Same version: relaunch keeps the daemon.
        job2, _ = sky.launch(_local_task('echo y'), cluster_name='tvg',
                             quiet_optimizer=True, detach_run=True)
        _wait_job('tvg', job2)
        with open(pid_file, encoding='utf-8') as f:
            assert int(f.read()) == pid1

        # Stale version: relaunch must replace the daemon.
        with open(os.path.join(agent_dir, 'agent.version'), 'w',
                  encoding='utf-8') as f:
            f.write('0')
        job3, _ = sky.launch(_local_task('echo z'), cluster_name='tvg',
                             quiet_optimizer=True, detach_run=True)
        _wait_job('tvg', job3)
        deadline = time.time() + 10
        while time.time() < deadline:
            with open(pid_file, encoding='utf-8') as f:
                pid2 = int(f.read())
            if pid2 != pid1:
                break
            time.sleep(0.25)
        assert pid2 != pid1, 'stale agent was not restarted'
        import psutil
        assert not psutil.pid_exists(pid1) or \
            psutil.Process(pid1).status() == psutil.STATUS_ZOMBIE
        sky.down('tvg')

    def test_resources_mismatch_on_reuse(self):
        t = _local_task('echo x')
        job_id, _ = sky.launch(t, cluster_name='t11', quiet_optimizer=True,
                               detach_run=True)
        _wait_job('t11', job_id)
        bigger = sky.Task(run='echo y', num_nodes=1)
        bigger.set_resources(
            sky.Resources(cloud='local', accelerators='tpu-v5e-8'))
        with pytest.raises(exceptions.ResourcesMismatchError):
            sky.launch(bigger, cluster_name='t11', quiet_optimizer=True,
                       detach_run=True)
        sky.down('t11')
