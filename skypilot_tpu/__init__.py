"""skypilot_tpu: a TPU-native cloud-orchestration + workload framework.

Public SDK (reference: sky/__init__.py:104-190):
    sky.launch / exec / status / start / stop / down / autostop
    sky.queue / cancel / tail_logs / download_logs / job_status
    sky.storage_ls / storage_delete / cost_report
    sky.Task / Resources / Dag / optimize
plus the TPU workload library under skypilot_tpu.{models,ops,parallel,train}.

Exports are lazy (PEP 562) so that on-cluster agent processes — which spawn
one interpreter per RPC (agent/rpc.py) — don't pay the full SDK import
cost (pandas/networkx) on every call.
"""
__version__ = '0.1.0'

_EXPORTS = {
    'Dag': ('skypilot_tpu.dag', 'Dag'),
    'Resources': ('skypilot_tpu.resources', 'Resources'),
    'Task': ('skypilot_tpu.task', 'Task'),
    'exceptions': ('skypilot_tpu.exceptions', None),
    'check': ('skypilot_tpu.check', 'check'),
    'autostop': ('skypilot_tpu.core', 'autostop'),
    'cancel': ('skypilot_tpu.core', 'cancel'),
    'cost_report': ('skypilot_tpu.core', 'cost_report'),
    'down': ('skypilot_tpu.core', 'down'),
    'download_logs': ('skypilot_tpu.core', 'download_logs'),
    'endpoints': ('skypilot_tpu.core', 'endpoints'),
    'job_status': ('skypilot_tpu.core', 'job_status'),
    'queue': ('skypilot_tpu.core', 'queue'),
    'start': ('skypilot_tpu.core', 'start'),
    'status': ('skypilot_tpu.core', 'status'),
    'stop': ('skypilot_tpu.core', 'stop'),
    'storage_delete': ('skypilot_tpu.core', 'storage_delete'),
    'storage_ls': ('skypilot_tpu.core', 'storage_ls'),
    'tail_logs': ('skypilot_tpu.core', 'tail_logs'),
    'exec': ('skypilot_tpu.execution', 'exec_'),
    'launch': ('skypilot_tpu.execution', 'launch'),
    'ClusterStatus': ('skypilot_tpu.global_user_state', 'ClusterStatus'),
    'Optimizer': ('skypilot_tpu.optimizer', 'Optimizer'),
    'OptimizeTarget': ('skypilot_tpu.optimizer', 'OptimizeTarget'),
    'optimize': ('skypilot_tpu.optimizer', 'optimize'),
    'jobs': ('skypilot_tpu.jobs', None),
    'serve': ('skypilot_tpu.serve', None),
}

__all__ = list(_EXPORTS) + ['__version__']


def __getattr__(name: str):
    if name in _EXPORTS:
        import importlib
        module_name, attr = _EXPORTS[name]
        module = importlib.import_module(module_name)
        value = module if attr is None else getattr(module, attr)
        globals()[name] = value  # cache
        return value
    raise AttributeError(f'module {__name__!r} has no attribute {name!r}')


def __dir__():
    return sorted(__all__)
