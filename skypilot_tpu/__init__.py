"""skypilot_tpu: a TPU-native cloud orchestration + workload framework."""
__version__ = '0.1.0'
