"""Serve load balancer: HTTP reverse proxy over the ready replicas.

Counterpart of the reference's sky/serve/load_balancer.py:22
`SkyServeLoadBalancer`: a reverse proxy that (a) forwards every request
to a replica chosen by the load-balancing policy, (b) aggregates request
timestamps, and (c) periodically syncs with the controller — posting the
aggregated stats and receiving the current ready-replica URL set.

Stdlib-only (ThreadingHTTPServer + urllib) instead of
FastAPI/uvicorn/httpx; streaming bodies are relayed in chunks.
"""
from __future__ import annotations

import http.client
import http.server
import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.serve import constants
from skypilot_tpu.serve import load_balancing_policies as lb_policies

logger = sky_logging.init_logger(__name__)

_HOP_HEADERS = {'connection', 'keep-alive', 'proxy-authenticate',
                'proxy-authorization', 'te', 'trailers',
                'transfer-encoding', 'upgrade', 'host', 'content-length'}

_PROBE_TIMEOUT_SECONDS = 3.0


from skypilot_tpu.utils import http_utils

LBHTTPServer = http_utils.HighBacklogHTTPServer


def _probe(replica_url: str) -> bool:
    """Probe a replica's ``GET /health``, honoring the three-state
    contract: only ``ok`` is routable.

    A bare TCP connect (the old probe) calls a DRAINING or UNHEALTHY
    replica healthy — its listener still accepts while admission sheds
    every request — so the LB kept routing to replicas that could only
    503.  A non-health-aware backend (connects but 404s /health) still
    counts as up, so the LB keeps working in front of plain HTTP
    services.
    """
    parsed = urllib.parse.urlparse(replica_url)
    if parsed.hostname is None:
        return False
    try:
        with urllib.request.urlopen(replica_url.rstrip('/') + '/health',
                                    timeout=_PROBE_TIMEOUT_SECONDS):
            return True
    except urllib.error.HTTPError as e:
        with e:
            # 503 carries draining/unhealthy — unroutable either way.
            # Any other status means the backend is up but does not
            # speak the health protocol; treat as routable.
            return e.code != 503
    except (urllib.error.URLError, ConnectionError, TimeoutError,
            OSError, http.client.HTTPException):
        return False


class RequestAggregator:
    """Sliding window of request timestamps (reference
    load_balancer.py request aggregator feeding the autoscaler)."""

    def __init__(self) -> None:
        self._timestamps: List[float] = []
        self._lock = threading.Lock()

    def add(self) -> None:
        with self._lock:
            self._timestamps.append(time.time())

    def drain(self) -> List[float]:
        with self._lock:
            out, self._timestamps = self._timestamps, []
            return out

    def requeue(self, timestamps: List[float]) -> None:
        """Return a drained batch after a failed sync (kept in order)."""
        with self._lock:
            self._timestamps = sorted(timestamps + self._timestamps)


class SkyServeLoadBalancer:

    def __init__(self, controller_url: str, port: int,
                 policy_name: str = 'round_robin',
                 sync_interval_seconds: float =
                 constants.LB_SYNC_INTERVAL_SECONDS,
                 replica_timeout_seconds: float =
                 constants.LB_REPLICA_TIMEOUT_SECONDS,
                 scale_from_zero_wait_seconds: float = 0.0) -> None:
        # scale_from_zero_wait_seconds > 0 ONLY for scale-to-zero
        # services (serve/service.py wires it); the default keeps the
        # empty-replica-set fast-503 for everything else.
        self.controller_url = controller_url.rstrip('/')
        self.port = port
        self.policy = lb_policies.LoadBalancingPolicy.from_name(policy_name)
        self.sync_interval = sync_interval_seconds
        self.replica_timeout = replica_timeout_seconds
        self.scale_from_zero_wait = scale_from_zero_wait_seconds
        self.aggregator = RequestAggregator()
        self._stop = threading.Event()
        self._server: Optional[http.server.ThreadingHTTPServer] = None
        self._threads: List[threading.Thread] = []
        # url -> monotonic expiry of a positive /health probe.  Only
        # successes are cached (and only briefly): back-to-back
        # requests skip the per-forward health roundtrip, while a
        # replica that failed its last probe is always re-probed fresh
        # so recovery and death are both seen immediately.
        self._probe_cache: dict = {}
        self._probe_lock = threading.Lock()

    def _probe_cached(self, url: str) -> bool:
        now = time.monotonic()
        with self._probe_lock:
            if self._probe_cache.get(url, 0.0) > now:
                return True
        ok = _probe(url)
        with self._probe_lock:
            if ok:
                self._probe_cache[url] = (
                    now + constants.LB_PROBE_CACHE_SECONDS)
            else:
                self._probe_cache.pop(url, None)
        return ok

    # -- controller sync ---------------------------------------------------
    def _sync_once(self) -> None:
        timestamps = self.aggregator.drain()
        payload = json.dumps({
            'request_aggregator': {'timestamps': timestamps}
        }).encode()
        req = urllib.request.Request(
            self.controller_url + '/controller/load_balancer_sync',
            data=payload, headers={'Content-Type': 'application/json'})
        try:
            with urllib.request.urlopen(req, timeout=5) as resp:
                data = json.loads(resp.read())
        except Exception:
            # A drained-but-unsent batch must survive a transient
            # controller outage: at scale-from-zero it can hold the
            # ONLY timestamp that wakes the service.
            self.aggregator.requeue(timestamps)
            raise
        self.policy.set_ready_replicas(data.get('ready_replica_urls', []))

    def _sync_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._sync_once()
            except Exception as e:  # pylint: disable=broad-except
                logger.warning(f'LB sync failed: {e}')
            self._stop.wait(self.sync_interval)

    # -- proxy -------------------------------------------------------------
    def _make_handler(self):
        lb = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = 'HTTP/1.1'

            def log_message(self, *args: Any) -> None:
                pass

            def _proxy(self) -> None:
                lb.aggregator.add()
                try:
                    length = int(self.headers.get('Content-Length', 0))
                except ValueError:
                    self._client_write(400, b'Bad Content-Length header.')
                    return
                data = self.rfile.read(length) if length > 0 else None
                # Dead-replica failover happens BEFORE the request is
                # forwarded: a /health probe (briefly cached when
                # positive) weeds out replicas whose host is gone or
                # that are draining.  Once a replica
                # accepts a connection the request is sent exactly once
                # — a timeout or reset after delivery is never retried,
                # so non-idempotent inference calls cannot run twice.
                tried: set = set()
                replica: Optional[str] = None
                for _ in range(constants.LB_MAX_ATTEMPTS):
                    cand = lb.policy.select_replica(exclude=tried)
                    if cand is None:
                        break
                    tried.add(cand)
                    if lb._probe_cached(cand):
                        replica = cand
                        break
                    logger.warning(f'Replica {cand} failed health probe; '
                                   'trying another replica.')
                if replica is None and not tried and \
                        lb.scale_from_zero_wait > 0:
                    # Scale-from-zero: this request's timestamp is
                    # already in the aggregator, so the controller
                    # will wake a replica — hold the request while
                    # the sync loop learns about it.
                    replica = self._await_wake()
                if replica is None:
                    if not tried:
                        self._client_write(
                            503, b'No ready replicas. Use "sky serve '
                                 b'status" to check the status.')
                    else:
                        self._client_write(
                            502, (f'All {len(tried)} attempted replicas '
                                  'unreachable.').encode())
                    return
                self._forward(replica, data)

            def _await_wake(self) -> Optional[str]:
                deadline = time.time() + lb.scale_from_zero_wait
                while time.time() < deadline:
                    cand = lb.policy.select_replica()
                    if cand is not None and lb._probe_cached(cand):
                        return cand
                    time.sleep(
                        constants.LB_SCALE_FROM_ZERO_POLL_SECONDS)
                return None

            def _client_write(self, code: int, body: bytes) -> None:
                """Send a full response; client-socket failures only
                close the connection (they must never look like replica
                failures)."""
                try:
                    self.send_response(code)
                    self.send_header('Content-Length', str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except OSError:
                    self.close_connection = True

            def _forward(self, replica: str, data) -> None:
                """Proxy the single delivery attempt; all failure modes
                terminate here (no retry once the request is sent)."""
                lb.policy.pre_execute_hook(replica)
                try:
                    headers = {k: v for k, v in self.headers.items()
                               if k.lower() not in _HOP_HEADERS}
                    req = urllib.request.Request(
                        replica + self.path, data=data, headers=headers,
                        method=self.command)
                    try:
                        resp = urllib.request.urlopen(
                            req, timeout=lb.replica_timeout)
                    except urllib.error.HTTPError as e:
                        # The replica *responded* (with an error
                        # status): forward it verbatim.
                        self._client_write(e.code, e.read())
                        return
                    except (urllib.error.URLError, ConnectionError,
                            TimeoutError, OSError,
                            http.client.HTTPException, ValueError) as e:
                        # OSError family: connection problems; HTTP-
                        # Exception: garbled replica response (e.g.
                        # BadStatusLine); ValueError: urllib URL
                        # validation.  All → 502, never a traceback.
                        self._client_write(
                            502, f'Replica request failed: {e}'.encode())
                        return
                    with resp:
                        self._stream_response(resp)
                finally:
                    lb.policy.post_execute_hook(replica)

            def _stream_response(self, resp) -> None:
                """Relay in chunks so token-streaming (SSE / chunked)
                inference responses reach the client incrementally.
                Once the status line is sent the request is no longer
                retryable, so mid-stream failures abort the connection
                instead of propagating to the retry loop."""
                try:
                    self.send_response(resp.status)
                    for k, v in resp.headers.items():
                        if k.lower() not in _HOP_HEADERS:
                            self.send_header(k, v)
                    length = resp.headers.get('Content-Length')
                    if length is not None:
                        self.send_header('Content-Length', length)
                        self.end_headers()
                    else:
                        self.send_header('Transfer-Encoding', 'chunked')
                        self.end_headers()
                    while True:
                        # read1: return as soon as one upstream chunk
                        # arrives (read() would block filling the whole
                        # buffer — no streaming).
                        chunk = resp.read1(64 * 1024)
                        if length is not None:
                            if not chunk:
                                break
                            self.wfile.write(chunk)
                        else:
                            if not chunk:
                                self.wfile.write(b'0\r\n\r\n')
                                break
                            self.wfile.write(
                                f'{len(chunk):x}\r\n'.encode())
                            self.wfile.write(chunk)
                            self.wfile.write(b'\r\n')
                        self.wfile.flush()
                except (OSError, ConnectionError, TimeoutError) as e:
                    logger.warning(f'Mid-stream proxy failure: {e}; '
                                   'closing client connection.')
                    self.close_connection = True

            do_GET = do_POST = do_PUT = do_DELETE = do_PATCH = _proxy

        return Handler

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._server = LBHTTPServer(
            ('0.0.0.0', self.port), self._make_handler())
        # 50ms serve poll: stop() blocks on shutdown() until the serve
        # loop next polls.
        serve = lambda: self._server.serve_forever(poll_interval=0.05)
        for target, name in ((serve, 'http'),
                             (self._sync_loop, 'sync')):
            t = threading.Thread(target=target, daemon=True,
                                 name=f'lb-{name}')
            t.start()
            self._threads.append(t)
        logger.info(f'Load balancer on port {self.port} -> '
                    f'{self.controller_url} ({self.policy.NAME}).')

    def stop(self) -> None:
        self._stop.set()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
