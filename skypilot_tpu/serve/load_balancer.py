"""Serve load balancer: HTTP reverse proxy over the ready replicas.

Counterpart of the reference's sky/serve/load_balancer.py:22
`SkyServeLoadBalancer`: a reverse proxy that (a) forwards every request
to a replica chosen by the load-balancing policy, (b) aggregates request
timestamps, and (c) periodically syncs with the controller — posting the
aggregated stats and receiving the current ready-replica URL set.

Stdlib-only (ThreadingHTTPServer + urllib) instead of
FastAPI/uvicorn/httpx; streaming bodies are relayed in chunks.
"""
from __future__ import annotations

import http.server
import json
import threading
import time
import urllib.error
import urllib.request
from typing import Any, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.serve import constants
from skypilot_tpu.serve import load_balancing_policies as lb_policies

logger = sky_logging.init_logger(__name__)

_HOP_HEADERS = {'connection', 'keep-alive', 'proxy-authenticate',
                'proxy-authorization', 'te', 'trailers',
                'transfer-encoding', 'upgrade', 'host', 'content-length'}


class RequestAggregator:
    """Sliding window of request timestamps (reference
    load_balancer.py request aggregator feeding the autoscaler)."""

    def __init__(self) -> None:
        self._timestamps: List[float] = []
        self._lock = threading.Lock()

    def add(self) -> None:
        with self._lock:
            self._timestamps.append(time.time())

    def drain(self) -> List[float]:
        with self._lock:
            out, self._timestamps = self._timestamps, []
            return out


class SkyServeLoadBalancer:

    def __init__(self, controller_url: str, port: int,
                 policy_name: str = 'round_robin',
                 sync_interval_seconds: float =
                 constants.LB_SYNC_INTERVAL_SECONDS) -> None:
        self.controller_url = controller_url.rstrip('/')
        self.port = port
        self.policy = lb_policies.LoadBalancingPolicy.from_name(policy_name)
        self.sync_interval = sync_interval_seconds
        self.aggregator = RequestAggregator()
        self._stop = threading.Event()
        self._server: Optional[http.server.ThreadingHTTPServer] = None
        self._threads: List[threading.Thread] = []

    # -- controller sync ---------------------------------------------------
    def _sync_once(self) -> None:
        payload = json.dumps({
            'request_aggregator': {
                'timestamps': self.aggregator.drain()
            }
        }).encode()
        req = urllib.request.Request(
            self.controller_url + '/controller/load_balancer_sync',
            data=payload, headers={'Content-Type': 'application/json'})
        with urllib.request.urlopen(req, timeout=5) as resp:
            data = json.loads(resp.read())
        self.policy.set_ready_replicas(data.get('ready_replica_urls', []))

    def _sync_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._sync_once()
            except Exception as e:  # pylint: disable=broad-except
                logger.warning(f'LB sync failed: {e}')
            self._stop.wait(self.sync_interval)

    # -- proxy -------------------------------------------------------------
    def _make_handler(self):
        lb = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = 'HTTP/1.1'

            def log_message(self, *args: Any) -> None:
                pass

            def _proxy(self) -> None:
                lb.aggregator.add()
                replica = lb.policy.select_replica()
                if replica is None:
                    body = b'No ready replicas. Use "sky serve status" ' \
                           b'to check the status.'
                    self.send_response(503)
                    self.send_header('Content-Length', str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                lb.policy.pre_execute_hook(replica)
                try:
                    length = int(self.headers.get('Content-Length', 0))
                    data = self.rfile.read(length) if length else None
                    headers = {k: v for k, v in self.headers.items()
                               if k.lower() not in _HOP_HEADERS}
                    req = urllib.request.Request(
                        replica + self.path, data=data, headers=headers,
                        method=self.command)
                    with urllib.request.urlopen(req, timeout=300) as resp:
                        # Relay in chunks so token-streaming (SSE /
                        # chunked) inference responses reach the client
                        # incrementally.
                        self.send_response(resp.status)
                        for k, v in resp.headers.items():
                            if k.lower() not in _HOP_HEADERS:
                                self.send_header(k, v)
                        length = resp.headers.get('Content-Length')
                        if length is not None:
                            self.send_header('Content-Length', length)
                            self.end_headers()
                        else:
                            self.send_header('Transfer-Encoding', 'chunked')
                            self.end_headers()
                        while True:
                            # read1: return as soon as one upstream
                            # chunk arrives (read() would block filling
                            # the whole buffer — no streaming).
                            chunk = resp.read1(64 * 1024)
                            if length is not None:
                                if not chunk:
                                    break
                                self.wfile.write(chunk)
                            else:
                                if not chunk:
                                    self.wfile.write(b'0\r\n\r\n')
                                    break
                                self.wfile.write(
                                    f'{len(chunk):x}\r\n'.encode())
                                self.wfile.write(chunk)
                                self.wfile.write(b'\r\n')
                            self.wfile.flush()
                except urllib.error.HTTPError as e:
                    body = e.read()
                    self.send_response(e.code)
                    self.send_header('Content-Length', str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except Exception as e:  # pylint: disable=broad-except
                    body = f'Replica request failed: {e}'.encode()
                    self.send_response(502)
                    self.send_header('Content-Length', str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                finally:
                    lb.policy.post_execute_hook(replica)

            do_GET = do_POST = do_PUT = do_DELETE = do_PATCH = _proxy

        return Handler

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._server = http.server.ThreadingHTTPServer(
            ('0.0.0.0', self.port), self._make_handler())
        self._server.daemon_threads = True
        for target, name in ((self._server.serve_forever, 'http'),
                             (self._sync_loop, 'sync')):
            t = threading.Thread(target=target, daemon=True,
                                 name=f'lb-{name}')
            t.start()
            self._threads.append(t)
        logger.info(f'Load balancer on port {self.port} -> '
                    f'{self.controller_url} ({self.policy.NAME}).')

    def stop(self) -> None:
        self._stop.set()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
