"""Autoscalers: QPS-driven replica-count decisions.

Counterpart of the reference's sky/serve/autoscalers.py: `Autoscaler`
ABC (:115), `RequestRateAutoscaler` (:431) — target QPS per replica with
upscale/downscale hysteresis counters (:348-429) — and
`FallbackRequestRateAutoscaler` (:546) — spot replicas with a base
on-demand fallback count plus dynamic on-demand backfill while spot
capacity is preempted.  Decisions are data (`ScaleUp(n)` /
`ScaleDown(ids)`), applied by the replica manager; the logic is pure so
it is unit-testable without clusters (mirrors
tests/test_serve_autoscaler.py in the reference).
"""
from __future__ import annotations

import dataclasses
import math
import time
import typing
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.serve import constants
from skypilot_tpu.serve import serve_state

if typing.TYPE_CHECKING:
    from skypilot_tpu.serve import service_spec as spec_lib

logger = sky_logging.init_logger(__name__)

# Statuses that count toward provisioned capacity (anything not on its
# way out).
_PROVISIONING_STATUSES = (serve_state.ReplicaStatus.PENDING,
                          serve_state.ReplicaStatus.PROVISIONING,
                          serve_state.ReplicaStatus.STARTING)
# NOT_READY replicas still hold a live cluster: they count as capacity
# (and are first in line for scale-down) until the prober/preemption
# path removes them.
_ALIVE_STATUSES = _PROVISIONING_STATUSES + (
    serve_state.ReplicaStatus.READY,
    serve_state.ReplicaStatus.NOT_READY)


@dataclasses.dataclass
class ScaleUpDecision:
    """Launch `count` new replicas (use_spot per the autoscaler's mix)."""
    count: int
    use_spot: bool = False


@dataclasses.dataclass
class ScaleDownDecision:
    """Terminate these replica ids."""
    replica_ids: List[int]


@dataclasses.dataclass
class AutoscalerDecision:
    scale_up: List[ScaleUpDecision] = dataclasses.field(default_factory=list)
    scale_down: List[ScaleDownDecision] = dataclasses.field(
        default_factory=list)

    @property
    def is_noop(self) -> bool:
        return not self.scale_up and not self.scale_down


def _alive(replicas: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    return [r for r in replicas if r['status'] in _ALIVE_STATUSES]


def _scale_down_order(replicas: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Broken/youngest first (reference replica_managers scale-down
    selection: keep the oldest READY replicas)."""
    order = {s: i for i, s in enumerate(
        serve_state.ReplicaStatus.scale_down_candidates())}
    return sorted(replicas,
                  key=lambda r: (order.get(r['status'], 99),
                                 -(r['launched_at'] or 0)))


class Autoscaler:
    """Base: fixed replica count = min_replicas (reference
    autoscalers.py:115 Autoscaler, which serves the no-autoscaling
    path)."""

    def __init__(self, spec: 'spec_lib.SkyServiceSpec') -> None:
        self.spec = spec
        self.update_spec(spec)

    def update_spec(self, spec: 'spec_lib.SkyServiceSpec') -> None:
        """Rolling update: adopt the new spec's policy in place."""
        self.spec = spec

    # -- request-stats intake (from the load balancer sync) ---------------
    def collect_request_information(
            self, request_timestamps: List[float]) -> None:
        del request_timestamps  # fixed-count autoscaler ignores traffic

    def evaluate_scaling(
            self, replicas: List[Dict[str, Any]]) -> AutoscalerDecision:
        alive = _alive(replicas)
        target = self.spec.min_replicas
        decision = AutoscalerDecision()
        if len(alive) < target:
            decision.scale_up.append(
                ScaleUpDecision(count=target - len(alive)))
        elif len(alive) > target:
            excess = _scale_down_order(alive)[:len(alive) - target]
            decision.scale_down.append(
                ScaleDownDecision([r['replica_id'] for r in excess]))
        return decision

    @classmethod
    def from_spec(cls, spec: 'spec_lib.SkyServiceSpec') -> 'Autoscaler':
        if spec.target_qps_per_replica is None:
            return Autoscaler(spec)
        if (spec.base_ondemand_fallback_replicas > 0 or
                spec.dynamic_ondemand_fallback):
            return FallbackRequestRateAutoscaler(spec)
        return RequestRateAutoscaler(spec)


class RequestRateAutoscaler(Autoscaler):
    """Reference autoscalers.py:431: target = ceil(qps /
    target_qps_per_replica), bounded to [min, max], applied only after
    the target has persisted for upscale_delay / downscale_delay
    seconds (hysteresis counters :348-429)."""

    def __init__(self, spec: 'spec_lib.SkyServiceSpec',
                 decision_interval_seconds: float =
                 constants.AUTOSCALER_INTERVAL_SECONDS,
                 qps_window_seconds: float =
                 constants.QPS_WINDOW_SECONDS) -> None:
        self.decision_interval = decision_interval_seconds
        self.qps_window = qps_window_seconds
        self.request_timestamps: List[float] = []
        self.upscale_counter = 0
        self.downscale_counter = 0
        super().__init__(spec)

    def update_spec(self, spec: 'spec_lib.SkyServiceSpec') -> None:
        super().update_spec(spec)
        self.scale_up_threshold = max(
            1, int(math.ceil(spec.upscale_delay_seconds /
                             self.decision_interval)))
        self.scale_down_threshold = max(
            1, int(math.ceil(spec.downscale_delay_seconds /
                             self.decision_interval)))

    def collect_request_information(
            self, request_timestamps: List[float]) -> None:
        self.request_timestamps.extend(request_timestamps)
        cutoff = time.time() - self.qps_window
        i = 0
        while (i < len(self.request_timestamps) and
               self.request_timestamps[i] < cutoff):
            i += 1
        del self.request_timestamps[:i]

    def _current_qps(self) -> float:
        return len(self.request_timestamps) / self.qps_window

    def _raw_target(self) -> int:
        qps = self._current_qps()
        assert self.spec.target_qps_per_replica is not None
        target = int(math.ceil(qps / self.spec.target_qps_per_replica))
        # Spec validation requires max_replicas with autoscaling; the
        # fallback (no scaling beyond min) is defense in depth.
        max_r = (self.spec.max_replicas
                 if self.spec.max_replicas is not None
                 else self.spec.min_replicas)
        return max(self.spec.min_replicas, min(max_r, target))

    def _hysteresis_target(self, current: int) -> int:
        """Move toward _raw_target only after it has persisted for the
        configured number of consecutive decisions."""
        target = self._raw_target()
        if target > current:
            self.upscale_counter += 1
            self.downscale_counter = 0
            if self.upscale_counter >= self.scale_up_threshold:
                self.upscale_counter = 0
                return target
        elif target < current:
            self.downscale_counter += 1
            self.upscale_counter = 0
            if self.downscale_counter >= self.scale_down_threshold:
                self.downscale_counter = 0
                return target
        else:
            self.upscale_counter = self.downscale_counter = 0
        return current

    def evaluate_scaling(
            self, replicas: List[Dict[str, Any]]) -> AutoscalerDecision:
        alive = _alive(replicas)
        current = len(alive)
        # Below min is not subject to hysteresis (cold start / failures).
        if current < self.spec.min_replicas:
            return AutoscalerDecision(scale_up=[ScaleUpDecision(
                count=self.spec.min_replicas - current)])
        # Scale-FROM-zero bypasses the upscale delay: with
        # min_replicas=0 the first request must wake the service
        # immediately — the requester is already waiting at the LB.
        # _raw_target is max-capped, so a (degenerate) max_replicas=0
        # spec stays at zero.
        if current == 0 and self._current_qps() > 0 and \
                self._raw_target() > 0:
            return AutoscalerDecision(scale_up=[ScaleUpDecision(
                count=self._raw_target())])
        target = self._hysteresis_target(current)
        decision = AutoscalerDecision()
        if target > current:
            decision.scale_up.append(ScaleUpDecision(count=target - current))
        elif target < current:
            excess = _scale_down_order(alive)[:current - target]
            decision.scale_down.append(
                ScaleDownDecision([r['replica_id'] for r in excess]))
        return decision


class FallbackRequestRateAutoscaler(RequestRateAutoscaler):
    """Reference autoscalers.py:546: serve traffic on spot replicas with
    `base_ondemand_fallback_replicas` always-on on-demand replicas;
    with `dynamic_ondemand_fallback`, temporarily backfill on-demand
    replicas 1:1 while spot replicas are provisioning/preempted."""

    def evaluate_scaling(
            self, replicas: List[Dict[str, Any]]) -> AutoscalerDecision:
        alive = _alive(replicas)
        spot = [r for r in alive if r['is_spot']]
        ondemand = [r for r in alive if not r['is_spot']]
        num_ready_spot = sum(
            1 for r in spot
            if r['status'] == serve_state.ReplicaStatus.READY)

        current = len(alive)
        if current < self.spec.min_replicas:
            target_total = self.spec.min_replicas
        elif current == 0 and self._current_qps() > 0 and \
                self._raw_target() > 0:
            # Scale-from-zero bypasses hysteresis here too (same
            # contract as the base autoscaler — the waker is blocked
            # at the LB).
            target_total = self._raw_target()
        else:
            target_total = self._hysteresis_target(current)

        base_od = min(self.spec.base_ondemand_fallback_replicas,
                      target_total)
        target_spot = target_total - base_od
        target_od = base_od
        if self.spec.dynamic_ondemand_fallback:
            # Backfill on-demand for every target spot replica not READY.
            target_od += max(0, target_spot - num_ready_spot)

        decision = AutoscalerDecision()
        if len(spot) < target_spot:
            decision.scale_up.append(ScaleUpDecision(
                count=target_spot - len(spot), use_spot=True))
        elif len(spot) > target_spot:
            excess = _scale_down_order(spot)[:len(spot) - target_spot]
            decision.scale_down.append(
                ScaleDownDecision([r['replica_id'] for r in excess]))
        if len(ondemand) < target_od:
            decision.scale_up.append(ScaleUpDecision(
                count=target_od - len(ondemand), use_spot=False))
        elif len(ondemand) > target_od:
            excess = _scale_down_order(ondemand)[:len(ondemand) - target_od]
            decision.scale_down.append(
                ScaleDownDecision([r['replica_id'] for r in excess]))
        return decision
