"""Serve state: SQLite tables + service/replica state machines.

Counterpart of the reference's sky/serve/serve_state.py (557 LoC):
`services` and `replicas` tables, `ServiceStatus` and `ReplicaStatus`
enums, and the version bookkeeping used for rolling updates
(sky/serve/replica_managers.py:1172).  As with managed jobs, the control
plane runs client-side (thread/process) instead of on a controller VM,
so the DB lives under the local state dir.
"""
from __future__ import annotations

import enum
import os
import sqlite3
import threading
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.utils import paths

logger = sky_logging.init_logger(__name__)

_lock = threading.RLock()

INITIAL_VERSION = 1


class ServiceStatus(enum.Enum):
    """Reference sky/serve/serve_state.py ServiceStatus."""
    CONTROLLER_INIT = 'CONTROLLER_INIT'
    REPLICA_INIT = 'REPLICA_INIT'
    READY = 'READY'
    NO_REPLICA = 'NO_REPLICA'
    SHUTTING_DOWN = 'SHUTTING_DOWN'
    FAILED = 'FAILED'
    CONTROLLER_FAILED = 'CONTROLLER_FAILED'

    def is_terminal(self) -> bool:
        return self in (ServiceStatus.FAILED,
                        ServiceStatus.CONTROLLER_FAILED)


class ReplicaStatus(enum.Enum):
    """Reference sky/serve/serve_state.py ReplicaStatus (driven by the
    `ReplicaStatusProperty` state machine, replica_managers.py:225)."""
    PENDING = 'PENDING'            # queued, not yet launching
    PROVISIONING = 'PROVISIONING'  # sky.launch in flight
    STARTING = 'STARTING'          # cluster UP, waiting on readiness probe
    READY = 'READY'                # probe passing
    NOT_READY = 'NOT_READY'        # probe failing post-READY
    SHUTTING_DOWN = 'SHUTTING_DOWN'
    PREEMPTED = 'PREEMPTED'
    FAILED = 'FAILED'              # launch or probe-deadline failure
    FAILED_CLEANUP = 'FAILED_CLEANUP'

    def is_terminal(self) -> bool:
        return self in (ReplicaStatus.FAILED, ReplicaStatus.FAILED_CLEANUP)

    @classmethod
    def scale_down_candidates(cls) -> List['ReplicaStatus']:
        """Order in which the autoscaler prefers to remove replicas:
        broken first, newest-READY last (reference
        replica_managers.py scale-down selection)."""
        return [cls.FAILED, cls.NOT_READY, cls.PREEMPTED, cls.PENDING,
                cls.PROVISIONING, cls.STARTING, cls.READY]


def serve_dir() -> str:
    d = os.path.join(paths.state_dir(), 'serve')
    os.makedirs(d, exist_ok=True)
    return d


def service_dir(service_name: str) -> str:
    d = os.path.join(serve_dir(), service_name)
    os.makedirs(d, exist_ok=True)
    return d


def _db_path() -> str:
    return os.path.join(serve_dir(), 'services.db')


_local = threading.local()


def _conn() -> sqlite3.Connection:
    path = _db_path()
    cache = getattr(_local, 'conns', None)
    if cache is None:
        cache = _local.conns = {}
    conn = cache.get(path)
    if conn is not None:
        return conn
    conn = sqlite3.connect(path, timeout=10)
    conn.execute("""CREATE TABLE IF NOT EXISTS services (
        name TEXT PRIMARY KEY,
        status TEXT,
        spec_yaml TEXT,
        task_yaml_path TEXT,
        version INTEGER DEFAULT 1,
        controller_port INTEGER,
        load_balancer_port INTEGER,
        controller_pid INTEGER,
        policy TEXT,
        requested_resources_str TEXT,
        submitted_at REAL)""")
    conn.execute("""CREATE TABLE IF NOT EXISTS replicas (
        service_name TEXT,
        replica_id INTEGER,
        status TEXT,
        cluster_name TEXT,
        endpoint TEXT,
        is_spot INTEGER DEFAULT 0,
        version INTEGER DEFAULT 1,
        launched_at REAL,
        ready_at REAL,
        consecutive_failures INTEGER DEFAULT 0,
        failure_reason TEXT,
        PRIMARY KEY (service_name, replica_id))""")
    conn.commit()
    cache[path] = conn
    return conn


def reset_for_tests() -> None:
    with _lock:
        cache = getattr(_local, 'conns', None)
        if cache:
            for conn in cache.values():
                conn.close()
            cache.clear()
        try:
            os.remove(_db_path())
        except FileNotFoundError:
            pass


# -- services --------------------------------------------------------------


def add_service(name: str, spec_yaml: str, task_yaml_path: str,
                controller_port: int, load_balancer_port: int,
                policy: str, requested_resources_str: str) -> bool:
    """Returns False if a service with this name already exists."""
    with _lock:
        try:
            _conn().execute(
                'INSERT INTO services (name, status, spec_yaml, '
                'task_yaml_path, version, controller_port, '
                'load_balancer_port, policy, requested_resources_str, '
                'submitted_at) VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?)',
                (name, ServiceStatus.CONTROLLER_INIT.value, spec_yaml,
                 task_yaml_path, INITIAL_VERSION, controller_port,
                 load_balancer_port, policy, requested_resources_str,
                 time.time()))
            _conn().commit()
            return True
        except sqlite3.IntegrityError:
            return False


def remove_service(name: str) -> None:
    with _lock:
        _conn().execute('DELETE FROM services WHERE name = ?', (name,))
        _conn().execute('DELETE FROM replicas WHERE service_name = ?',
                        (name,))
        _conn().commit()


def set_service_status(name: str, status: ServiceStatus) -> None:
    with _lock:
        _conn().execute('UPDATE services SET status = ? WHERE name = ?',
                        (status.value, name))
        _conn().commit()


def set_service_controller_pid(name: str, pid: int) -> None:
    with _lock:
        _conn().execute(
            'UPDATE services SET controller_pid = ? WHERE name = ?',
            (pid, name))
        _conn().commit()


def set_service_version(name: str, version: int,
                        spec_yaml: Optional[str] = None,
                        task_yaml_path: Optional[str] = None) -> None:
    with _lock:
        _conn().execute('UPDATE services SET version = ? WHERE name = ?',
                        (version, name))
        if spec_yaml is not None:
            _conn().execute(
                'UPDATE services SET spec_yaml = ? WHERE name = ?',
                (spec_yaml, name))
        if task_yaml_path is not None:
            _conn().execute(
                'UPDATE services SET task_yaml_path = ? WHERE name = ?',
                (task_yaml_path, name))
        _conn().commit()


_SERVICE_COLS = ('name', 'status', 'spec_yaml', 'task_yaml_path', 'version',
                 'controller_port', 'load_balancer_port', 'controller_pid',
                 'policy', 'requested_resources_str', 'submitted_at')


def _service_row_to_dict(row: tuple) -> Dict[str, Any]:
    rec = dict(zip(_SERVICE_COLS, row))
    rec['status'] = ServiceStatus(rec['status'])
    return rec


def get_service(name: str) -> Optional[Dict[str, Any]]:
    cols = ', '.join(_SERVICE_COLS)
    row = _conn().execute(
        f'SELECT {cols} FROM services WHERE name = ?', (name,)).fetchone()
    return _service_row_to_dict(row) if row else None


def get_services() -> List[Dict[str, Any]]:
    cols = ', '.join(_SERVICE_COLS)
    rows = _conn().execute(
        f'SELECT {cols} FROM services ORDER BY submitted_at').fetchall()
    return [_service_row_to_dict(r) for r in rows]


def max_used_port(column: str) -> Optional[int]:
    assert column in ('controller_port', 'load_balancer_port')
    row = _conn().execute(f'SELECT MAX({column}) FROM services').fetchone()
    return row[0]


# -- replicas --------------------------------------------------------------

_REPLICA_COLS = ('service_name', 'replica_id', 'status', 'cluster_name',
                 'endpoint', 'is_spot', 'version', 'launched_at', 'ready_at',
                 'consecutive_failures', 'failure_reason')


def add_replica(service_name: str, replica_id: int, cluster_name: str,
                is_spot: bool, version: int) -> None:
    with _lock:
        _conn().execute(
            'INSERT OR REPLACE INTO replicas (service_name, replica_id, '
            'status, cluster_name, is_spot, version, launched_at) '
            'VALUES (?, ?, ?, ?, ?, ?, ?)',
            (service_name, replica_id, ReplicaStatus.PENDING.value,
             cluster_name, int(is_spot), version, time.time()))
        _conn().commit()


def remove_replica(service_name: str, replica_id: int) -> None:
    with _lock:
        _conn().execute(
            'DELETE FROM replicas WHERE service_name = ? AND '
            'replica_id = ?', (service_name, replica_id))
        _conn().commit()


def set_replica_status(service_name: str, replica_id: int,
                       status: ReplicaStatus,
                       failure_reason: Optional[str] = None) -> None:
    with _lock:
        _conn().execute(
            'UPDATE replicas SET status = ? WHERE service_name = ? AND '
            'replica_id = ?',
            (status.value, service_name, replica_id))
        if status == ReplicaStatus.READY:
            _conn().execute(
                'UPDATE replicas SET ready_at = ?, consecutive_failures = 0 '
                'WHERE service_name = ? AND replica_id = ?',
                (time.time(), service_name, replica_id))
        if failure_reason is not None:
            _conn().execute(
                'UPDATE replicas SET failure_reason = ? WHERE '
                'service_name = ? AND replica_id = ?',
                (failure_reason, service_name, replica_id))
        _conn().commit()


def set_replica_endpoint(service_name: str, replica_id: int,
                         endpoint: str) -> None:
    with _lock:
        _conn().execute(
            'UPDATE replicas SET endpoint = ? WHERE service_name = ? AND '
            'replica_id = ?', (endpoint, service_name, replica_id))
        _conn().commit()


def bump_replica_failures(service_name: str, replica_id: int) -> int:
    """Increment and return the consecutive probe-failure count."""
    with _lock:
        _conn().execute(
            'UPDATE replicas SET consecutive_failures = '
            'consecutive_failures + 1 WHERE service_name = ? AND '
            'replica_id = ?', (service_name, replica_id))
        _conn().commit()
        row = _conn().execute(
            'SELECT consecutive_failures FROM replicas WHERE '
            'service_name = ? AND replica_id = ?',
            (service_name, replica_id)).fetchone()
        return row[0] if row else 0


def clear_replica_failures(service_name: str, replica_id: int) -> None:
    with _lock:
        _conn().execute(
            'UPDATE replicas SET consecutive_failures = 0 WHERE '
            'service_name = ? AND replica_id = ?',
            (service_name, replica_id))
        _conn().commit()


def _replica_row_to_dict(row: tuple) -> Dict[str, Any]:
    rec = dict(zip(_REPLICA_COLS, row))
    rec['status'] = ReplicaStatus(rec['status'])
    rec['is_spot'] = bool(rec['is_spot'])
    return rec


def get_replica(service_name: str,
                replica_id: int) -> Optional[Dict[str, Any]]:
    cols = ', '.join(_REPLICA_COLS)
    row = _conn().execute(
        f'SELECT {cols} FROM replicas WHERE service_name = ? AND '
        'replica_id = ?', (service_name, replica_id)).fetchone()
    return _replica_row_to_dict(row) if row else None


def get_replicas(service_name: str) -> List[Dict[str, Any]]:
    cols = ', '.join(_REPLICA_COLS)
    rows = _conn().execute(
        f'SELECT {cols} FROM replicas WHERE service_name = ? ORDER BY '
        'replica_id', (service_name,)).fetchall()
    return [_replica_row_to_dict(r) for r in rows]


def next_replica_id(service_name: str) -> int:
    row = _conn().execute(
        'SELECT MAX(replica_id) FROM replicas WHERE service_name = ?',
        (service_name,)).fetchone()
    return (row[0] or 0) + 1


def total_replicas_launched(service_name: str) -> int:
    row = _conn().execute(
        'SELECT COUNT(*) FROM replicas WHERE service_name = ?',
        (service_name,)).fetchone()
    return row[0]
