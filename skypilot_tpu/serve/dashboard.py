"""SkyServe dashboard: zero-dependency HTTP view of services+replicas.

Beats the reference here: it ships only a managed-jobs dashboard
(sky/jobs/dashboard/), so `sky serve status` has no browsable analog.
Same design as jobs/dashboard.py — stdlib ThreadingHTTPServer, inert
textContent rendering, JSON API under the HTML — and the snapshot
routes are ALSO mounted on every serve controller (`/services`,
`/api/services`), so a running service is inspectable without a
separate process.

Routes:
  GET /              HTML page (auto-refreshing services + replicas,
                     plus the data-plane fleet when --router is set).
  GET /api/services  JSON: [{service record, replicas: [...]}, ...].
  GET /api/fleet     JSON fleet snapshot proxied from the router's
                     observability surfaces (/router/replicas +
                     /fleet/slo + /fleet/profile); 404 unless started
                     with --router.
  GET /healthz       liveness probe.

Fleet mode (``--router http://host:port``) points the dashboard at a
``serve/router.py`` data plane: per-replica health/breaker/queue rows
from ``/router/replicas`` and SLO goodput + burn rate from
``/fleet/slo``.  The serve_state mode above remains for control-plane
(SkyServe) services; deep metric browsing belongs to ``/fleet/metrics``
on the router, which any Prometheus can federate directly.
"""
from __future__ import annotations

import enum
import html
import http.server
import json
import threading
import time
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import sky_logging
from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve import serve_utils

logger = sky_logging.init_logger(__name__)

DEFAULT_PORT = 5051

_FLEET_FETCH_TIMEOUT_S = 5.0


def _jsonable(row: Dict[str, Any]) -> Dict[str, Any]:
    return {k: (v.value if isinstance(v, enum.Enum) else v)
            for k, v in row.items()}


def services_snapshot(
        service_name: Optional[str] = None) -> List[Dict[str, Any]]:
    """Every service (or just one) with its replica rows — the same
    truth `sky serve status` prints, as JSON."""
    records = serve_state.get_services() if service_name is None else \
        [r for r in [serve_state.get_service(service_name)]
         if r is not None]
    out = []
    for rec in records:
        replicas = [_jsonable(r)
                    for r in serve_state.get_replicas(rec['name'])]
        entry = _jsonable(rec)
        entry.pop('spec_yaml', None)  # bulky; API serves the summary
        entry['endpoint'] = serve_utils.get_endpoint(rec)
        entry['replicas'] = replicas
        entry['n_ready'] = sum(1 for r in replicas
                               if r['status'] == 'READY')
        out.append(entry)
    return out


def fleet_snapshot(router_url: str) -> Dict[str, Any]:
    """One JSON document for the data-plane fleet: the router's replica
    views plus its SLO accounting.  Unreachable halves degrade to an
    'error' field instead of failing the whole snapshot — the dashboard
    must stay useful mid-incident."""
    base = router_url.rstrip('/')
    out: Dict[str, Any] = {'router': base}
    for key, path in (('replicas', '/router/replicas'),
                      ('slo', '/fleet/slo'),
                      ('profile', '/fleet/profile')):
        try:
            with urllib.request.urlopen(
                    base + path,
                    timeout=_FLEET_FETCH_TIMEOUT_S) as resp:
                out[key] = json.loads(resp.read())
        except Exception as e:  # pylint: disable=broad-except
            out[key] = {'error': repr(e)}
    out['cache'] = _cache_tier_by_replica(base)
    return out


def _cache_tier_by_replica(base: str) -> Dict[str, Dict[str, Any]]:
    """Per-replica host-tier prefix-cache stats distilled from the
    router's federated /fleet/metrics (every series there carries a
    ``replica`` label).  Replicas running without the tier publish no
    skytpu_fleet_cache_* series at all and simply don't appear — the
    dashboard renders '-' for them."""
    try:
        with urllib.request.urlopen(
                base + '/fleet/metrics',
                timeout=_FLEET_FETCH_TIMEOUT_S) as resp:
            parsed = metrics_lib.parse_exposition(
                resp.read().decode('utf-8', 'replace'))
    except Exception:  # pylint: disable=broad-except
        return {}
    per: Dict[str, Dict[str, float]] = {}
    for name, key in (('skytpu_fleet_cache_hits_total', 'hits'),
                      ('skytpu_fleet_cache_misses_total', 'misses'),
                      ('skytpu_fleet_cache_spilled_bytes_total',
                       'spilled_bytes'),
                      ('skytpu_fleet_cache_stored_bytes',
                       'stored_bytes')):
        for labels, value in parsed.get(name, {}).items():
            url = dict(labels).get('replica')
            if url:
                per.setdefault(url, {})[key] = value
    out: Dict[str, Dict[str, Any]] = {}
    for url, vals in per.items():
        lookups = vals.get('hits', 0.0) + vals.get('misses', 0.0)
        out[url] = {
            'hit_rate': (round(vals.get('hits', 0.0) / lookups, 4)
                         if lookups else None),
            'spilled_bytes': vals.get('spilled_bytes', 0.0),
            'stored_bytes': vals.get('stored_bytes', 0.0),
        }
    return out


_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>SkyServe services</title>
<style>
 body {{ font-family: system-ui, sans-serif; margin: 2em; color: #222; }}
 table {{ border-collapse: collapse; width: 100%; margin-bottom: 1.5em; }}
 th, td {{ text-align: left; padding: 6px 10px;
           border-bottom: 1px solid #ddd; font-size: 14px; }}
 th {{ background: #f5f5f5; }}
 .READY {{ color: #1a7f37; }} .STARTING, .PROVISIONING,
 .REPLICA_INIT, .PENDING {{ color: #9a6700; }}
 .FAILED, .PREEMPTED, .SHUTTING_DOWN {{ color: #cf222e; }}
 .NO_REPLICA, .NOT_READY {{ color: #6e7781; }}
 #meta {{ color: #6e7781; font-size: 13px; margin-bottom: 1em; }}
 h3 {{ margin-bottom: 4px; }}
</style></head>
<body>
<h2>SkyServe services</h2>
<div id="meta">auto-refreshing every 5s</div>
<div id="services">{body}</div>
<div id="fleet"></div>
<script>
// Service/replica fields are user-controlled (names, endpoints):
// build nodes with textContent, never innerHTML.
function cell(text, cls) {{
  const td = document.createElement('td');
  td.textContent = text;
  if (cls) td.className = cls;
  return td;
}}
function table(headers, rows) {{
  const t = document.createElement('table');
  const tr = document.createElement('tr');
  headers.forEach(h => {{
    const th = document.createElement('th'); th.textContent = h;
    tr.append(th);
  }});
  t.createTHead().append(tr);
  const tb = t.createTBody();
  rows.forEach(r => tb.append(r));
  return t;
}}
async function refresh() {{
  try {{
    const r = await fetch('/api/services');
    const svcs = await r.json();
    const root = document.querySelector('#services');
    root.replaceChildren(...svcs.flatMap(s => {{
      const h = document.createElement('h3');
      h.textContent = s.name + ' — ' + s.status + ' (' + s.n_ready +
        ' ready) · ' + (s.endpoint ?? '');
      const rows = s.replicas.map(rep => {{
        const tr = document.createElement('tr');
        tr.append(cell(rep.replica_id), cell(rep.cluster_name ?? '-'),
                  cell(rep.version ?? '-'),
                  cell(rep.endpoint ?? '-'),
                  cell(rep.status,
                       /^[A-Z_]+$/.test(rep.status) ? rep.status : ''),
                  cell(rep.consecutive_failures ?? 0));
        return tr;
      }});
      return [h, table(['ID', 'Cluster', 'Version', 'Endpoint',
                        'Status', '#Failures'], rows)];
    }}));
    document.querySelector('#meta').textContent =
      svcs.length + ' services · refreshed ' +
      new Date().toLocaleTimeString();
  }} catch (e) {{ /* controller restarting; retry next tick */ }}
}}
async function refreshFleet() {{
  const root = document.querySelector('#fleet');
  try {{
    const r = await fetch('/api/fleet');
    if (!r.ok) return;  // fleet mode not configured
    const f = await r.json();
    const h = document.createElement('h3');
    h.textContent = 'Data-plane fleet · ' + f.router;
    const reps = f.replicas.replicas ?? [];
    // Host-tier prefix-cache columns come from the federated
    // /fleet/metrics distillation; replicas without the tier have
    // no entry and render '-'.
    const cache = f.cache ?? {{}};
    // MFU / step-p99 columns come from the router's /fleet/profile
    // step-ledger roll-up; replicas with an empty (or disabled)
    // ledger window render '-'.
    const prof = {{}};
    (f.profile && f.profile.replicas || []).forEach(p => {{
      prof[p.replica] = p;
    }});
    const fmtB = n => n >= 1048576 ?
      (n / 1048576).toFixed(1) + ' MiB' : n >= 1024 ?
      (n / 1024).toFixed(1) + ' KiB' : n + ' B';
    const rows = reps.map(rep => {{
      const tr = document.createElement('tr');
      const c = cache[rep.url];
      const p = prof[rep.url];
      const mfuCell = cell(p && p.steps ?
        (100 * p.achieved_mfu).toFixed(2) + '%' : '-');
      const p99Cell = cell(p && p.steps ?
        p.step_ms_p99.toFixed(1) + ' ms' : '-');
      if (p && p.steps && p.roofline_verdict) {{
        // Roofline verdict rides as a tooltip, not a column: the
        // mix fractions give the 'mostly memory-bound' nuance.
        const tip = p.roofline_verdict + ' (' +
          (100 * p.roofline.memory_bound).toFixed(0) + '% mem / ' +
          (100 * p.roofline.compute_bound).toFixed(0) + '% compute)';
        mfuCell.title = tip;
        p99Cell.title = tip;
      }}
      tr.append(cell(rep.url), cell(rep.role ?? 'both'),
                cell(rep.health),
                cell(rep.circuit), cell(rep.inflight),
                cell(rep.queue_depth ?? '-'),
                cell(rep.free_pages ?? '-'),
                cell(c && c.hit_rate != null ?
                     (100 * c.hit_rate).toFixed(1) + '%' : '-'),
                cell(c ? fmtB(c.spilled_bytes) : '-'),
                mfuCell, p99Cell,
                cell(rep.routable ? 'yes' : 'no'));
      return tr;
    }});
    // Disaggregated-fleet pool aggregates: the prefill pool scales on
    // queue depth, the decode pool on page starvation — surface both
    // signals the way the autoscaler reads them.
    const pools = document.createElement('div');
    const inPool = (rep, roles) => roles.includes(rep.role ?? 'both');
    const pre = reps.filter(r => inPool(r, ['prefill', 'both']));
    const dec = reps.filter(r => inPool(r, ['decode', 'both']));
    const sum = (rs, k) => rs.reduce((a, r) => a + (r[k] ?? 0), 0);
    pools.textContent =
      'Pools: prefill×' + pre.length +
      ' (queue depth ' + sum(pre, 'queue_depth') + ') · decode×' +
      dec.length + ' (free pages ' + sum(dec, 'free_pages') + ')';
    const slo = document.createElement('div');
    const slos = f.slo.slos ?? {{}};
    slo.textContent = 'SLO (target ' +
      (f.slo.goodput_target ?? '-') + '): ' +
      Object.entries(slos).map(([k, v]) =>
        k + ' goodput ' + (v.goodput ?? 1).toFixed(4) +
        ' burn ' + (v.burn_rate ?? 0).toFixed(2)).join(' · ');
    root.replaceChildren(h, pools,
      table(['URL', 'Role', 'Health', 'Breaker', 'In-flight', 'Queue',
             'Free pages', 'Cache hit', 'Spilled', 'MFU', 'Step p99',
             'Routable'],
            rows), slo);
  }} catch (e) {{ /* router restarting; retry next tick */ }}
}}
refresh(); setInterval(refresh, 5000);
refreshFleet(); setInterval(refreshFleet, 5000);
</script>
</body></html>
"""


def render_index(service_name: Optional[str] = None) -> str:
    """Server-side first paint (JS keeps it fresh afterwards)."""
    parts = []
    for svc in services_snapshot(service_name):
        parts.append(
            f'<h3>{html.escape(str(svc["name"]))} — '
            f'{html.escape(str(svc["status"]))} '
            f'({svc["n_ready"]} ready) · '
            f'{html.escape(str(svc.get("endpoint") or ""))}</h3>')
        rows = []
        for rep in svc['replicas']:
            status = str(rep['status'])
            rows.append('<tr>' + ''.join(
                f'<td{cls}>{html.escape(str(v))}</td>'
                for v, cls in [
                    (rep['replica_id'], ''),
                    (rep.get('cluster_name') or '-', ''),
                    (rep.get('version') or '-', ''),
                    (rep.get('endpoint') or '-', ''),
                    (status, f' class="{status}"'),
                    (rep.get('consecutive_failures') or 0, ''),
                ]) + '</tr>')
        parts.append(
            '<table><tr><th>ID</th><th>Cluster</th><th>Version</th>'
            '<th>Endpoint</th><th>Status</th><th>#Failures</th></tr>'
            + ''.join(rows) + '</table>')
    return _PAGE.format(body=''.join(parts))


_GET_ROUTES = ('/', '/healthz', '/api/services', '/api/fleet')


class _Handler(http.server.BaseHTTPRequestHandler):

    # Set by start(): router base URL for fleet mode, or None.
    router_url: Optional[str] = None

    def log_message(self, fmt: str, *args: Any) -> None:
        logger.debug('serve-dashboard: ' + fmt % args)

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header('Content-Type', ctype)
        self.send_header('Content-Length', str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 (stdlib API name)
        path = self.path.split('?', 1)[0].rstrip('/') or '/'
        try:
            if path == '/':
                self._send(200, render_index().encode(), 'text/html')
            elif path == '/healthz':
                self._send(200, b'{"ok": true}', 'application/json')
            elif path == '/api/services':
                self._send(200,
                           json.dumps(services_snapshot()).encode(),
                           'application/json')
            elif path == '/api/fleet':
                if self.router_url is None:
                    self._send(404, b'{"error": "fleet mode off; '
                                    b'start with --router URL"}',
                               'application/json')
                else:
                    self._send(
                        200,
                        json.dumps(
                            fleet_snapshot(self.router_url)).encode(),
                        'application/json')
            else:
                self._send(404, b'{"error": "not found"}',
                           'application/json')
        except OSError:
            pass  # client went away mid-write

    def do_POST(self) -> None:  # noqa: N802 (stdlib API name)
        # Read-only server: a POST to a known page gets an explicit
        # 405+Allow (the stdlib default is a bare 501, which retry
        # classifiers read as a server bug), anything else a 404.
        path = self.path.split('?', 1)[0].rstrip('/') or '/'
        try:
            if path in _GET_ROUTES:
                self.send_response(405)
                self.send_header('Allow', 'GET')
                self.send_header('Content-Length', '0')
                self.end_headers()
            else:
                self._send(404, b'{"error": "not found"}',
                           'application/json')
        except OSError:
            pass  # client went away mid-write


def start(host: str = '127.0.0.1',
          port: int = DEFAULT_PORT,
          router_url: Optional[str] = None
          ) -> Tuple[http.server.ThreadingHTTPServer, threading.Thread]:
    """Standalone dashboard (all services) in a daemon thread; callers
    own shutdown.  port=0 binds ephemeral (tests).  ``router_url``
    turns on fleet mode (/api/fleet + the fleet page section)."""
    handler = type('_BoundHandler', (_Handler,),
                   {'router_url': router_url})
    server = http.server.ThreadingHTTPServer((host, port), handler)
    server.daemon_threads = True
    thread = threading.Thread(target=server.serve_forever,
                              name='serve-dashboard', daemon=True)
    thread.start()
    logger.info('Serve dashboard at http://%s:%d',
                host, server.server_address[1])
    return server, thread


def serve_forever(host: str = '127.0.0.1',
                  port: int = DEFAULT_PORT,
                  router_url: Optional[str] = None) -> None:
    server, thread = start(host, port, router_url=router_url)
    try:
        thread.join()
    finally:
        server.shutdown()
        server.server_close()


if __name__ == '__main__':
    import argparse
    parser = argparse.ArgumentParser()
    parser.add_argument('--host', default='127.0.0.1')
    parser.add_argument('--port', type=int, default=DEFAULT_PORT)
    parser.add_argument('--router', default=None,
                        help='Router base URL (e.g. http://host:8080) '
                             'to show the data-plane fleet: replica '
                             'health/breakers plus /fleet/slo goodput.')
    args = parser.parse_args()
    serve_forever(args.host, args.port, router_url=args.router)
