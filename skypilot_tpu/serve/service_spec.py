"""Service spec for the serve subsystem.

Counterpart of the reference's sky/serve/service_spec.py:18 SkyServiceSpec:
readiness probe (path / POST payload / headers / initial delay), replica
policy (min/max, target QPS per replica, scale delays, spot + on-demand
fallback mix), load-balancing policy.
"""
from __future__ import annotations

import json
from typing import Any, Dict, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.utils import schemas

DEFAULT_INITIAL_DELAY_SECONDS = 1200
DEFAULT_READINESS_TIMEOUT_SECONDS = 15
DEFAULT_UPSCALE_DELAY_SECONDS = 300
DEFAULT_DOWNSCALE_DELAY_SECONDS = 1200


class SkyServiceSpec:

    def __init__(
        self,
        readiness_path: str,
        initial_delay_seconds: float = DEFAULT_INITIAL_DELAY_SECONDS,
        readiness_timeout_seconds: float = DEFAULT_READINESS_TIMEOUT_SECONDS,
        min_replicas: int = 1,
        max_replicas: Optional[int] = None,
        target_qps_per_replica: Optional[float] = None,
        post_data: Optional[Any] = None,
        readiness_headers: Optional[Dict[str, str]] = None,
        upscale_delay_seconds: float = DEFAULT_UPSCALE_DELAY_SECONDS,
        downscale_delay_seconds: float = DEFAULT_DOWNSCALE_DELAY_SECONDS,
        base_ondemand_fallback_replicas: int = 0,
        dynamic_ondemand_fallback: bool = False,
        load_balancing_policy: Optional[str] = None,
        port: int = 8080,
    ) -> None:
        if not readiness_path.startswith('/'):
            raise exceptions.TaskValidationError(
                f'Readiness path must start with /: {readiness_path!r}')
        if min_replicas < 0:
            raise exceptions.TaskValidationError(
                'min_replicas must be >= 0.')
        if min_replicas == 0 and target_qps_per_replica is None:
            raise exceptions.TaskValidationError(
                'min_replicas=0 (scale-to-zero) requires '
                'target_qps_per_replica so traffic can wake the '
                'service.')
        if max_replicas is not None and max_replicas < min_replicas:
            raise exceptions.TaskValidationError(
                'max_replicas must be >= min_replicas.')
        if target_qps_per_replica is not None and \
                target_qps_per_replica <= 0:
            raise exceptions.TaskValidationError(
                'target_qps_per_replica must be positive.')
        if target_qps_per_replica is not None and max_replicas is None:
            raise exceptions.TaskValidationError(
                'max_replicas is required when target_qps_per_replica is '
                'set: autoscaling without an upper bound could launch an '
                'unbounded number of TPU clusters.')
        self.readiness_path = readiness_path
        self.initial_delay_seconds = initial_delay_seconds
        self.readiness_timeout_seconds = readiness_timeout_seconds
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.target_qps_per_replica = target_qps_per_replica
        self.post_data = post_data
        self.readiness_headers = readiness_headers or {}
        self.upscale_delay_seconds = upscale_delay_seconds
        self.downscale_delay_seconds = downscale_delay_seconds
        self.base_ondemand_fallback_replicas = base_ondemand_fallback_replicas
        self.dynamic_ondemand_fallback = dynamic_ondemand_fallback
        self.load_balancing_policy = load_balancing_policy or 'round_robin'
        self.port = port

    @property
    def autoscaling_enabled(self) -> bool:
        return self.target_qps_per_replica is not None

    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any]) -> 'SkyServiceSpec':
        schemas.validate(config, schemas.get_service_schema(),
                         exceptions.TaskValidationError,
                         'Invalid service: ')
        probe = config['readiness_probe']
        if isinstance(probe, str):
            probe = {'path': probe}
        policy = dict(config.get('replica_policy') or {})
        if 'replicas' in config:  # fixed-replica shorthand
            policy.setdefault('min_replicas', config['replicas'])
            policy.setdefault('max_replicas', config['replicas'])
        return cls(
            readiness_path=probe['path'],
            initial_delay_seconds=probe.get(
                'initial_delay_seconds', DEFAULT_INITIAL_DELAY_SECONDS),
            readiness_timeout_seconds=probe.get(
                'timeout_seconds', DEFAULT_READINESS_TIMEOUT_SECONDS),
            post_data=probe.get('post_data'),
            readiness_headers=probe.get('headers'),
            min_replicas=policy.get('min_replicas', 1),
            max_replicas=policy.get('max_replicas'),
            target_qps_per_replica=policy.get('target_qps_per_replica'),
            upscale_delay_seconds=policy.get(
                'upscale_delay_seconds', DEFAULT_UPSCALE_DELAY_SECONDS),
            downscale_delay_seconds=policy.get(
                'downscale_delay_seconds', DEFAULT_DOWNSCALE_DELAY_SECONDS),
            base_ondemand_fallback_replicas=policy.get(
                'base_ondemand_fallback_replicas', 0),
            dynamic_ondemand_fallback=policy.get(
                'dynamic_ondemand_fallback', False),
            load_balancing_policy=config.get('load_balancing_policy'),
            port=config.get('port', 8080),
        )

    def to_yaml_config(self) -> Dict[str, Any]:
        probe: Dict[str, Any] = {'path': self.readiness_path}
        if self.initial_delay_seconds != DEFAULT_INITIAL_DELAY_SECONDS:
            probe['initial_delay_seconds'] = self.initial_delay_seconds
        if self.post_data is not None:
            probe['post_data'] = self.post_data
        if self.readiness_headers:
            probe['headers'] = self.readiness_headers
        policy: Dict[str, Any] = {'min_replicas': self.min_replicas}
        if self.max_replicas is not None:
            policy['max_replicas'] = self.max_replicas
        if self.target_qps_per_replica is not None:
            policy['target_qps_per_replica'] = self.target_qps_per_replica
        if self.base_ondemand_fallback_replicas:
            policy['base_ondemand_fallback_replicas'] = \
                self.base_ondemand_fallback_replicas
        if self.dynamic_ondemand_fallback:
            policy['dynamic_ondemand_fallback'] = True
        return {
            'readiness_probe': probe,
            'replica_policy': policy,
            'load_balancing_policy': self.load_balancing_policy,
            'port': self.port,
        }

    def __repr__(self) -> str:
        return (f'SkyServiceSpec(path={self.readiness_path}, '
                f'replicas=[{self.min_replicas}, {self.max_replicas}], '
                f'qps/replica={self.target_qps_per_replica})')
