"""Load-balancing policies (reference: sky/serve/load_balancing_policies.py).

`LoadBalancingPolicy` ABC (:32) with `round_robin` and
`least_number_of_requests` implementations, selected by name from the
service spec.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

POLICIES = {}


def register(name: str):
    def deco(cls):
        POLICIES[name] = cls
        cls.NAME = name
        return cls
    return deco


class LoadBalancingPolicy:
    """Tracks the ready-replica set and picks a target per request."""
    NAME = 'abstract'

    def __init__(self) -> None:
        self.ready_replicas: List[str] = []
        self._lock = threading.Lock()

    def set_ready_replicas(self, replicas: List[str]) -> None:
        with self._lock:
            if set(replicas) != set(self.ready_replicas):
                self._on_replicas_changed(replicas)
            self.ready_replicas = list(replicas)

    def _on_replicas_changed(self, replicas: List[str]) -> None:
        pass

    def select_replica(self, exclude: Optional[set] = None
                       ) -> Optional[str]:
        """Pick a target; `exclude` skips replicas the current request
        already failed against (LB connection-retry support)."""
        raise NotImplementedError

    def pre_execute_hook(self, replica: str) -> None:
        pass

    def post_execute_hook(self, replica: str) -> None:
        pass

    @classmethod
    def from_name(cls, name: str) -> 'LoadBalancingPolicy':
        if name not in POLICIES:
            raise ValueError(
                f'Unknown load balancing policy {name!r}; '
                f'available: {sorted(POLICIES)}')
        return POLICIES[name]()


@register('round_robin')
class RoundRobinPolicy(LoadBalancingPolicy):
    """Reference load_balancing_policies.py round_robin."""

    def __init__(self) -> None:
        super().__init__()
        self._index = 0

    def _on_replicas_changed(self, replicas: List[str]) -> None:
        self._index = 0

    def select_replica(self, exclude: Optional[set] = None
                       ) -> Optional[str]:
        with self._lock:
            pool = [r for r in self.ready_replicas
                    if not exclude or r not in exclude]
            if not pool:
                return None
            replica = pool[self._index % len(pool)]
            self._index = (self._index + 1) % max(
                1, len(self.ready_replicas))
            return replica


@register('least_number_of_requests')
class LeastNumberOfRequestsPolicy(LoadBalancingPolicy):
    """Reference load_balancing_policies.py least_number_of_requests:
    route to the replica with the fewest in-flight requests."""

    def __init__(self) -> None:
        super().__init__()
        self._inflight: Dict[str, int] = {}

    def select_replica(self, exclude: Optional[set] = None
                       ) -> Optional[str]:
        with self._lock:
            pool = [r for r in self.ready_replicas
                    if not exclude or r not in exclude]
            if not pool:
                return None
            return min(pool, key=lambda r: self._inflight.get(r, 0))

    def pre_execute_hook(self, replica: str) -> None:
        with self._lock:
            self._inflight[replica] = self._inflight.get(replica, 0) + 1

    def post_execute_hook(self, replica: str) -> None:
        with self._lock:
            self._inflight[replica] = max(
                0, self._inflight.get(replica, 0) - 1)
