"""SkyServe-analog: multi-replica serving with autoscaling + LB
(reference: sky/serve/, §2.7 of SURVEY.md)."""
from skypilot_tpu.serve.core import down
from skypilot_tpu.serve.core import status
from skypilot_tpu.serve.core import tail_logs
from skypilot_tpu.serve.core import up
from skypilot_tpu.serve.core import update
from skypilot_tpu.serve.serve_state import ReplicaStatus
from skypilot_tpu.serve.serve_state import ServiceStatus
from skypilot_tpu.serve.service_spec import SkyServiceSpec

__all__ = [
    'down', 'status', 'tail_logs', 'up', 'update',
    'ReplicaStatus', 'ServiceStatus', 'SkyServiceSpec',
]
