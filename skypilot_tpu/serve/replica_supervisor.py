"""Replica fleet supervisor: crash restarts, drain scale-down, chaos.

Owns the lifecycle of the router's replica fleet so the data plane
self-heals end to end:

* **Crash restarts** — a replica process that exits without being
  asked to is restarted with jittered exponential backoff
  (``utils/retry.compute_delay``), up to ``restart_budget`` restarts
  per slot inside a rolling ``restart_window_s``.  A slot that blows
  its budget is FAILED and stays down — a crash-looping binary must
  not burn the host forever (same budget shape as the in-replica
  decode-loop supervisor).
* **Scale events** — the supervisor holds the fleet at the
  autoscaler's desired size.  Scale-up spawns a fresh replica (the
  router's health loop admits it once ``/health`` says ok).  Scale-down
  NEVER drops a request: the router stops routing to the victim first
  (``mark_draining``), then ``POST /drain`` lets in-flight work finish
  and the process exit on its own; only a drain-deadline overrun
  escalates to SIGTERM.
* **Chaos** — the ``replica_kill`` fault point SIGKILLs a live replica
  from inside the supervision loop, so the whole
  crash → reroute → restart → re-admit cycle is provable in tests
  without an external killer.

Replica processes are created by a ``factory(slot_id) -> (handle,
url)`` callable; ``handle`` needs the ``subprocess.Popen`` surface
(``poll``/``terminate``/``kill``).  Tests substitute in-process fakes;
production uses :func:`subprocess_replica_factory`.

The autoscaler here is **metrics-driven**: it reads the engine-native
load signals the router already scrapes (decode queue depth, free KV
pages) instead of request rate — queue depth is what actually predicts
TTFT on a continuous-batching engine.  The spec/QPS autoscalers in
``serve/autoscalers.py`` serve the control plane; this one serves the
data plane and shares its hysteresis shape (consecutive-evaluation
patience in both directions, scale-up more eager than scale-down).
"""
from __future__ import annotations

import json
import os
import random
import subprocess
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

from skypilot_tpu import sky_logging
from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.serve import constants
from skypilot_tpu.serve.router import Router
from skypilot_tpu.utils import chaos
from skypilot_tpu.utils import retry as retry_lib

logger = sky_logging.init_logger(__name__)

# Slot states.
LIVE = 'live'            # process spawned (router decides routability)
BACKOFF = 'backoff'      # crashed; waiting out the restart delay
DRAINING = 'draining'    # asked to drain; waiting for self-exit
STOPPED = 'stopped'      # scale-down complete
FAILED = 'failed'        # restart budget exhausted; stays down


def _supervisor_metrics(registry: Optional[metrics_lib.Registry] = None):
    r = registry if registry is not None else metrics_lib.get_registry()
    return {
        'restarts': r.counter(
            'skytpu_router_replica_restarts_total',
            'Replica processes restarted by the supervisor after an '
            'unexpected exit.'),
        'scale_events': r.counter(
            'skytpu_router_scale_events_total',
            'Autoscaler-driven fleet size changes, by direction.',
            labelnames=('direction',)),
        'desired': r.gauge(
            'skytpu_router_desired_replicas',
            'Fleet size the autoscaler currently wants.'),
    }


class EngineSignalsAutoscaler:
    """Desired fleet size from scraped engine signals, with hysteresis.

    Scale up one replica when the mean decode queue depth across
    routable replicas has exceeded ``queue_high`` for
    ``upscale_patience`` consecutive evaluations (a saturated page pool
    with queued work counts as high load too — no free pages means
    admission is already blocking).  Scale down one replica when the
    mean has stayed below ``queue_low`` for ``downscale_patience``
    evaluations.  Asymmetric patience: adding capacity late costs TTFT
    SLOs, removing it late costs only money.

    ``signal`` picks what "pressure" means, so a disaggregated fleet
    can scale its two pools on what each actually runs out of:

    * ``'queue'`` (default) — prefill-shaped load: queue depth is what
      predicts TTFT when admission is prefill-bound.
    * ``'pages'`` — decode-shaped load: a decode-role replica stalls
      on KV page starvation (handoffs waiting on free pages), not on
      queue depth; pressure is any routable replica with zero free
      pages and queued work, and scale-down additionally requires no
      replica anywhere near starvation.
    """

    def __init__(self, min_replicas: int = 1,
                 max_replicas: Optional[int] = None,
                 queue_high: float = constants.AUTOSCALE_QUEUE_HIGH,
                 queue_low: float = constants.AUTOSCALE_QUEUE_LOW,
                 upscale_patience: int =
                 constants.AUTOSCALE_UPSCALE_PATIENCE,
                 downscale_patience: int =
                 constants.AUTOSCALE_DOWNSCALE_PATIENCE,
                 signal: str = 'queue'):
        if min_replicas < 1:
            raise ValueError('min_replicas must be >= 1')
        if max_replicas is not None and max_replicas < min_replicas:
            raise ValueError('max_replicas must be >= min_replicas')
        if signal not in ('queue', 'pages'):
            raise ValueError(
                f"signal must be 'queue' or 'pages', got {signal!r}")
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.queue_high = queue_high
        self.queue_low = queue_low
        self.upscale_patience = upscale_patience
        self.downscale_patience = downscale_patience
        self.signal = signal
        self._over = 0
        self._under = 0

    def desired(self, views, current: int) -> int:
        """One evaluation: the new desired size given the router's
        replica views and the current fleet size."""
        current = max(current, 0)
        routable = [v for v in views if v.routable]
        if not routable:
            # Blind: hold the fleet, let supervision restore health.
            self._over = self._under = 0
            return max(current, self.min_replicas)
        mean_depth = sum(v.queue_depth for v in routable) / len(routable)
        starved = any(v.free_pages == 0.0 and v.queue_depth > 0
                      for v in routable)
        if self.signal == 'pages':
            high = starved
            low = (not starved) and mean_depth <= self.queue_low
        else:
            high = mean_depth >= self.queue_high or starved
            low = mean_depth <= self.queue_low
        if high:
            self._over += 1
            self._under = 0
        elif low:
            self._under += 1
            self._over = 0
        else:
            self._over = self._under = 0
        target = current
        if self._over >= self.upscale_patience:
            target = current + 1
            self._over = 0
        elif self._under >= self.downscale_patience and \
                current > self.min_replicas:
            target = current - 1
            self._under = 0
        if self.max_replicas is not None:
            target = min(target, self.max_replicas)
        return max(target, self.min_replicas)


class _Slot:

    def __init__(self, slot_id: int, role: str = 'both'):
        self.slot_id = slot_id
        self.role = role             # both | prefill | decode
        self.state = BACKOFF         # spawn happens on the next tick
        self.handle = None
        self.url: Optional[str] = None
        self.restart_times: List[float] = []
        self.next_start_at = 0.0
        self.drain_deadline = 0.0

    def __repr__(self):
        return (f'_Slot({self.slot_id}, {self.state}, '
                f'role={self.role}, url={self.url}, '
                f'restarts={len(self.restart_times)})')


class ReplicaSupervisor:
    """Drives the fleet toward the autoscaler's desired size and keeps
    every slot alive (or declared dead).  ``tick()`` is the whole
    control loop and is public so tests can step it deterministically;
    ``start()`` runs it on a daemon thread every ``tick_s``."""

    def __init__(self, factory: Callable[[int], Tuple[object, str]],
                 router: Router,
                 min_replicas: int = 1,
                 max_replicas: Optional[int] = None,
                 autoscaler: Optional[EngineSignalsAutoscaler] = None,
                 tick_s: float = constants.SUPERVISOR_TICK_SECONDS,
                 restart_base_delay_s: float =
                 constants.SUPERVISOR_RESTART_BASE_DELAY_SECONDS,
                 restart_max_delay_s: float =
                 constants.SUPERVISOR_RESTART_MAX_DELAY_SECONDS,
                 restart_budget: int = constants.SUPERVISOR_RESTART_BUDGET,
                 restart_window_s: float =
                 constants.SUPERVISOR_RESTART_WINDOW_SECONDS,
                 drain_timeout_s: float =
                 constants.SUPERVISOR_DRAIN_TIMEOUT_SECONDS,
                 registry: Optional[metrics_lib.Registry] = None,
                 rng: Optional[random.Random] = None,
                 pools: Optional[Dict[str, dict]] = None):
        self._factory = factory
        self.router = router
        # Disaggregated fleets: ``pools`` maps a replica role
        # ('prefill' / 'decode' / 'both') to a per-pool config dict
        # ({'min_replicas': N, 'max_replicas': M, 'autoscaler': ...}).
        # Each pool scales independently on its own signal (prefill on
        # queue depth, decode on page starvation), victims are picked
        # inside the shrinking pool only, and a crashed slot respawns
        # with its own role.  The factory is then called as
        # factory(slot_id, role).  Without ``pools`` everything
        # behaves exactly as before (single homogeneous pool,
        # factory(slot_id)).
        self._pools = dict(pools) if pools else None
        if self._pools:
            for role, cfg in self._pools.items():
                if role not in ('both', 'prefill', 'decode'):
                    raise ValueError(f'unknown pool role {role!r}')
                if not isinstance(cfg, dict):
                    raise ValueError(
                        f'pool {role!r} config must be a dict')
            min_replicas = sum(
                int(cfg.get('min_replicas', 1))
                for cfg in self._pools.values())
            max_replicas = None
            autoscaler = None
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.autoscaler = autoscaler
        self.tick_s = tick_s
        self.restart_base_delay_s = restart_base_delay_s
        self.restart_max_delay_s = restart_max_delay_s
        self.restart_budget = restart_budget
        self.restart_window_s = restart_window_s
        self.drain_timeout_s = drain_timeout_s
        self._rng = rng if rng is not None else random.Random()
        self._met = _supervisor_metrics(registry)
        self._lock = threading.Lock()
        self._slots: Dict[int, _Slot] = {}
        self._next_slot_id = 0
        self.desired = min_replicas
        self._met['desired'].set(self.desired)
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if self._pools:
            for role, cfg in self._pools.items():
                for _ in range(int(cfg.get('min_replicas', 1))):
                    self._new_slot(role)
        else:
            for _ in range(min_replicas):
                self._new_slot()

    # -- slot bookkeeping ---------------------------------------------
    def _new_slot(self, role: str = 'both') -> _Slot:
        with self._lock:
            slot = _Slot(self._next_slot_id, role=role)
            self._next_slot_id += 1
            self._slots[slot.slot_id] = slot
        return slot

    def slots(self) -> List[_Slot]:
        with self._lock:
            return list(self._slots.values())

    def _active(self) -> List[_Slot]:
        """Slots that count toward fleet size (spawned or respawning —
        draining/failed/stopped ones are already on their way out)."""
        return [s for s in self.slots() if s.state in (LIVE, BACKOFF)]

    # -- lifecycle ----------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name='skytpu-replica-sup')
        self._thread.start()

    def stop(self, kill_replicas: bool = True) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if kill_replicas:
            for slot in self.slots():
                if slot.handle is not None and slot.handle.poll() is None:
                    slot.handle.terminate()
            deadline = time.monotonic() + 5
            for slot in self.slots():
                if slot.handle is None:
                    continue
                while slot.handle.poll() is None and \
                        time.monotonic() < deadline:
                    time.sleep(0.02)
                if slot.handle.poll() is None:
                    slot.handle.kill()

    def _loop(self) -> None:
        while not self._stop_evt.wait(self.tick_s):
            try:
                self.tick()
            except Exception:  # pylint: disable=broad-except
                logger.exception('supervisor tick failed')

    # -- the control loop ---------------------------------------------
    def tick(self) -> None:
        self._maybe_chaos_kill()
        self._reap_and_schedule_restarts()
        self._spawn_pending()
        self._finish_drains()
        self._autoscale()

    def _maybe_chaos_kill(self) -> None:
        live = [s for s in self.slots()
                if s.state == LIVE and s.handle is not None
                and s.handle.poll() is None]
        if live and chaos.should_inject('replica_kill'):
            victim = self._rng.choice(live)
            notice = float(
                os.environ.get('SKYTPU_PREEMPT_NOTICE_S', '0') or 0)
            if notice > 0 and victim.url is not None:
                # TPU-preemption shape: spot VMs get a short notice
                # window before the plug is pulled.  Spend it on a
                # migrate-drain so in-flight slots checkpoint to
                # survivors instead of losing their KV mid-stream.
                survivors = self._survivor_urls(victim)
                logger.warning(
                    f'chaos: preempting replica slot {victim.slot_id} '
                    f'({victim.url}) with {notice:.1f}s notice, '
                    f'{len(survivors)} survivor(s)')
                self.router.mark_draining(victim.url)
                self._post_drain(victim.url, survivors)
                time.sleep(notice)
            logger.warning(
                f'chaos: SIGKILLing replica slot {victim.slot_id} '
                f'({victim.url})')
            victim.handle.kill()

    def _reap_and_schedule_restarts(self) -> None:
        now = time.monotonic()
        for slot in self.slots():
            if slot.state != LIVE or slot.handle is None:
                continue
            code = slot.handle.poll()
            if code is None:
                continue
            # Unexpected exit: reroute first, then decide restart.
            if slot.url is not None:
                self.router.remove_replica(slot.url)
            slot.restart_times = [
                t for t in slot.restart_times
                if now - t <= self.restart_window_s]
            slot.restart_times.append(now)
            if len(slot.restart_times) > self.restart_budget:
                slot.state = FAILED
                logger.error(
                    f'replica slot {slot.slot_id} exceeded its restart '
                    f'budget ({self.restart_budget} within '
                    f'{self.restart_window_s:.0f}s); giving the slot up')
                continue
            delay = retry_lib.compute_delay(
                len(slot.restart_times) - 1,
                base_delay_s=self.restart_base_delay_s,
                max_delay_s=self.restart_max_delay_s,
                jitter='full', rng=self._rng)
            slot.state = BACKOFF
            slot.next_start_at = now + delay
            self._met['restarts'].inc()
            self.router.events.record(
                'replica_restart', slot=slot.slot_id, url=slot.url,
                exit_code=code, delay_s=round(delay, 3),
                restarts_in_window=len(slot.restart_times))
            logger.warning(
                f'replica slot {slot.slot_id} exited with code {code}; '
                f'restarting in {delay:.2f}s '
                f'(restart {len(slot.restart_times)}/'
                f'{self.restart_budget})')

    def _spawn_pending(self) -> None:
        now = time.monotonic()
        for slot in self.slots():
            if slot.state != BACKOFF or now < slot.next_start_at:
                continue
            try:
                if self._pools:
                    handle, url = self._factory(slot.slot_id,
                                                slot.role)
                else:
                    handle, url = self._factory(slot.slot_id)
            except Exception:  # pylint: disable=broad-except
                logger.exception(
                    f'spawn failed for replica slot {slot.slot_id}; '
                    'will retry next tick')
                slot.next_start_at = now + self.restart_base_delay_s
                continue
            slot.handle = handle
            slot.url = url.rstrip('/')
            slot.state = LIVE
            self.router.add_replica(slot.url)
            self.router.events.record(
                'replica_spawn', slot=slot.slot_id, url=slot.url)
            logger.info(
                f'replica slot {slot.slot_id} spawned at {slot.url}')

    # -- scale-down via drain -----------------------------------------
    def _survivor_urls(self, victim: _Slot) -> List[str]:
        """Live replicas a drain/preemption can migrate the victim's
        in-flight slots to — anything /handoff-capable (role both or
        decode) that is not the victim itself."""
        return [s.url for s in self.slots()
                if s is not victim and s.state == LIVE
                and s.url is not None
                and s.role in ('both', 'decode')
                and s.handle is not None and s.handle.poll() is None]

    def _post_drain(self, url: str, survivors: List[str]) -> None:
        """POST /drain, asking for live migration when survivors
        exist (a non-migratable replica quietly finishes locally
        instead).  Failures fall back to the drain deadline."""
        payload = json.dumps({
            'migrate': bool(survivors),
            'targets': survivors,
        }).encode()
        try:
            req = urllib.request.Request(
                url + '/drain', data=payload, method='POST',
                headers={'Content-Type': 'application/json'})
            urllib.request.urlopen(req, timeout=5).close()
        except (urllib.error.URLError, urllib.error.HTTPError,
                ConnectionError, TimeoutError, OSError):
            # Unreachable for drain == already dead; escalation
            # cleans up.
            logger.warning(
                f'drain request to {url} failed; falling back '
                'to the drain deadline')

    def _begin_drain(self, slot: _Slot) -> None:
        slot.state = DRAINING
        slot.drain_deadline = time.monotonic() + self.drain_timeout_s
        if slot.url is not None:
            # Unroutable BEFORE the drain request: zero requests may
            # land on the victim after this point.
            self.router.mark_draining(slot.url)
            self._post_drain(slot.url, self._survivor_urls(slot))

    def _finish_drains(self) -> None:
        now = time.monotonic()
        for slot in self.slots():
            if slot.state != DRAINING:
                continue
            exited = slot.handle is None or slot.handle.poll() is not None
            if not exited and now > slot.drain_deadline:
                logger.warning(
                    f'replica slot {slot.slot_id} missed its drain '
                    f'deadline; terminating')
                slot.handle.terminate()
                exited = True
            if exited:
                slot.state = STOPPED
                if slot.url is not None:
                    self.router.remove_replica(slot.url)
                logger.info(
                    f'replica slot {slot.slot_id} drained and stopped')

    def _autoscale(self) -> None:
        if self._pools:
            self._autoscale_pools()
            return
        active = self._active()
        if self.autoscaler is not None:
            self.desired = self.autoscaler.desired(
                self.router.views(), len(active))
        else:
            self.desired = max(self.min_replicas, len(active))
        if self.max_replicas is not None:
            self.desired = min(self.desired, self.max_replicas)
        self._met['desired'].set(self.desired)
        if len(active) < self.desired:
            for _ in range(self.desired - len(active)):
                self._new_slot()
            self._met['scale_events'].labels(direction='up').inc()
            self.router.events.record(
                'scale_up', desired=self.desired, was=len(active))
            logger.info(f'scaling up to {self.desired} replica(s)')
        elif len(active) > self.desired:
            # Newest-first victims (oldest replicas hold the warmest
            # prefix caches and the most compile cache residency).
            victims = sorted(
                (s for s in active if s.state == LIVE),
                key=lambda s: -s.slot_id)[:len(active) - self.desired]
            if victims:
                self._met['scale_events'].labels(direction='down').inc()
                self.router.events.record(
                    'scale_down', desired=self.desired,
                    was=len(active),
                    victims=[s.slot_id for s in victims])
            for slot in victims:
                logger.info(
                    f'scaling down: draining replica slot '
                    f'{slot.slot_id} ({slot.url})')
                self._begin_drain(slot)

    def _autoscale_pools(self) -> None:
        """Per-pool autoscaling for disaggregated fleets: each pool
        sees only its own replicas' views (role learned by the router
        from /health?verbose=1 — undiscovered replicas still read as
        'both' and scale with that pool), scales on its own signal,
        and shrinks by draining its own newest slots only."""
        views = self.router.views()
        total = 0
        for role, cfg in sorted(self._pools.items()):
            active = [s for s in self._active() if s.role == role]
            pool_min = int(cfg.get('min_replicas', 1))
            scaler = cfg.get('autoscaler')
            if scaler is not None:
                pool_views = [v for v in views if v.role == role]
                want = scaler.desired(pool_views, len(active))
            else:
                want = max(pool_min, len(active))
            pool_max = cfg.get('max_replicas')
            if pool_max is not None:
                want = min(want, int(pool_max))
            want = max(want, pool_min)
            total += want
            if len(active) < want:
                for _ in range(want - len(active)):
                    self._new_slot(role)
                self._met['scale_events'].labels(direction='up').inc()
                self.router.events.record(
                    'scale_up', pool=role, desired=want,
                    was=len(active))
                logger.info(
                    f'scaling {role} pool up to {want} replica(s)')
            elif len(active) > want:
                victims = sorted(
                    (s for s in active if s.state == LIVE),
                    key=lambda s: -s.slot_id)[:len(active) - want]
                if victims:
                    self._met['scale_events'].labels(
                        direction='down').inc()
                    self.router.events.record(
                        'scale_down', pool=role, desired=want,
                        was=len(active),
                        victims=[s.slot_id for s in victims])
                for slot in victims:
                    logger.info(
                        f'scaling {role} pool down: draining replica '
                        f'slot {slot.slot_id} ({slot.url})')
                    self._begin_drain(slot)
        self.desired = total
        self._met['desired'].set(self.desired)


def subprocess_replica_factory(argv_template: List[str],
                               host: str = '127.0.0.1',
                               port_start: int =
                               constants.LOCAL_REPLICA_PORT_START,
                               env: Optional[Dict[str, str]] = None
                               ) -> Callable[[int], Tuple[object, str]]:
    """Factory spawning real ``infer.server`` subprocesses.

    ``argv_template`` entries may contain ``{port}`` / ``{slot_id}``
    placeholders.  Each spawn (including a restart of the same slot)
    takes the next free port — the old port may linger in TIME_WAIT.
    """
    counter = {'n': 0}
    lock = threading.Lock()

    def factory(slot_id: int) -> Tuple[object, str]:
        with lock:
            port = port_start + counter['n']
            counter['n'] += 1
        argv = [a.format(port=port, slot_id=slot_id)
                for a in argv_template]
        proc = subprocess.Popen(argv, env=env)
        return proc, f'http://{host}:{port}'

    return factory
