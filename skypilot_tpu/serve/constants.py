"""Serve subsystem constants (reference: sky/serve/constants.py)."""

# Port ranges for locally-hosted control processes.  The reference runs
# the controller/LB on a dedicated controller VM with fixed ports
# (sky/serve/constants.py); here the control plane may share a host with
# other services, so each service gets the next free port in the range.
CONTROLLER_PORT_START = 20001
LOAD_BALANCER_PORT_START = 30001

# Replica port range used for local-cloud replicas (every replica shares
# the host's network namespace, so each needs its own port).  On real
# clouds every replica has its own IP and the service spec's single port
# is used as-is.
LOCAL_REPLICA_PORT_START = 40001

# Controller loop intervals (seconds).  The reference probes every 10 s
# and runs the autoscaler every 20 s (sky/serve/constants.py); tests
# override these to sub-second via ControllerConfig.
AUTOSCALER_INTERVAL_SECONDS = 20.0
PROBE_INTERVAL_SECONDS = 10.0
LB_SYNC_INTERVAL_SECONDS = 20.0
# Per-attempt replica timeout (urllib blocking-op timeout; generous for
# long token-streaming inference responses) and how many *distinct*
# replicas one request may TCP-probe before 502.  Failover happens at
# the probe stage only: once a replica accepts a connection the request
# is delivered exactly once, so non-idempotent inference calls can
# never execute twice.
LB_REPLICA_TIMEOUT_SECONDS = 300.0
LB_MAX_ATTEMPTS = 3
# With min_replicas=0 the first request arrives while no replica
# exists; the LB holds it while the autoscaler wakes one (cold starts
# include provisioning) instead of bouncing 503 at the waker.
LB_SCALE_FROM_ZERO_WAIT_SECONDS = 120.0
LB_SCALE_FROM_ZERO_POLL_SECONDS = 2.0

# Consecutive probe failures before READY -> NOT_READY.
PROBE_FAILURE_THRESHOLD = 3

# QPS window for autoscaling decisions (reference
# autoscalers.py qps_window_size = 60).
QPS_WINDOW_SECONDS = 60.0

# Env vars injected into replica tasks.
REPLICA_PORT_ENV = 'SKYTPU_SERVE_REPLICA_PORT'
REPLICA_ID_ENV = 'SKYTPU_SERVE_REPLICA_ID'
SERVICE_NAME_ENV = 'SKYTPU_SERVE_SERVICE_NAME'
