"""Serve subsystem constants (reference: sky/serve/constants.py)."""

# Port ranges for locally-hosted control processes.  The reference runs
# the controller/LB on a dedicated controller VM with fixed ports
# (sky/serve/constants.py); here the control plane may share a host with
# other services, so each service gets the next free port in the range.
CONTROLLER_PORT_START = 20001
LOAD_BALANCER_PORT_START = 30001

# Replica port range used for local-cloud replicas (every replica shares
# the host's network namespace, so each needs its own port).  On real
# clouds every replica has its own IP and the service spec's single port
# is used as-is.
LOCAL_REPLICA_PORT_START = 40001

# Controller loop intervals (seconds).  The reference probes every 10 s
# and runs the autoscaler every 20 s (sky/serve/constants.py); tests
# override these to sub-second via ControllerConfig.
AUTOSCALER_INTERVAL_SECONDS = 20.0
PROBE_INTERVAL_SECONDS = 10.0
LB_SYNC_INTERVAL_SECONDS = 20.0
# Per-attempt replica timeout (urllib blocking-op timeout; generous for
# long token-streaming inference responses) and how many *distinct*
# replicas one request may TCP-probe before 502.  Failover happens at
# the probe stage only: once a replica accepts a connection the request
# is delivered exactly once, so non-idempotent inference calls can
# never execute twice.
LB_REPLICA_TIMEOUT_SECONDS = 300.0
LB_MAX_ATTEMPTS = 3
# How long a POSITIVE /health probe is trusted before the next forward
# re-probes.  Caps the per-request probe overhead under burst traffic;
# kept short so a replica that starts draining stops receiving new
# requests almost immediately.  Failures are never cached.
LB_PROBE_CACHE_SECONDS = 0.25
# With min_replicas=0 the first request arrives while no replica
# exists; the LB holds it while the autoscaler wakes one (cold starts
# include provisioning) instead of bouncing 503 at the waker.
LB_SCALE_FROM_ZERO_WAIT_SECONDS = 120.0
LB_SCALE_FROM_ZERO_POLL_SECONDS = 2.0

# Consecutive probe failures before READY -> NOT_READY.
PROBE_FAILURE_THRESHOLD = 3

# QPS window for autoscaling decisions (reference
# autoscalers.py qps_window_size = 60).
QPS_WINDOW_SECONDS = 60.0

# Env vars injected into replica tasks.
REPLICA_PORT_ENV = 'SKYTPU_SERVE_REPLICA_PORT'
REPLICA_ID_ENV = 'SKYTPU_SERVE_REPLICA_ID'
SERVICE_NAME_ENV = 'SKYTPU_SERVE_SERVICE_NAME'

# -- Self-healing router (serve/router.py) ---------------------------
# Health loop cadence and per-probe timeout.  The health probe is a
# GET /health against an in-process handler — 2 s of silence already
# means the replica is wedged, not slow.
ROUTER_HEALTH_INTERVAL_SECONDS = 1.0
ROUTER_HEALTH_TIMEOUT_SECONDS = 2.0
# Per-delivery-attempt urllib timeout.  Generous like
# LB_REPLICA_TIMEOUT_SECONDS: a streaming generation holds the
# connection for its full decode.
ROUTER_ATTEMPT_TIMEOUT_SECONDS = 300.0
# Failover budget per request: rounds of (every untried routable
# replica back-to-back), with jittered backoff — floored by any shed's
# Retry-After — between rounds, all under a wall-clock budget capped by
# the request's own deadline_s.
ROUTER_MAX_ROUNDS = 3
ROUTER_REQUEST_BUDGET_SECONDS = 120.0
# Circuit breaker: consecutive delivery failures that open a replica's
# circuit, and how long it stays open before a half-open trial.
ROUTER_CB_FAILURE_THRESHOLD = 3
ROUTER_CB_COOLDOWN_SECONDS = 5.0
# Prefix-affinity granularity (token ids per chunk) used until the
# fleet reports its real KV page size via /health?verbose=1.
ROUTER_AFFINITY_PAGE_SIZE = 16
# A replica whose scraped decode queue depth reaches this is
# "saturated": affinity stops pinning requests to it.
ROUTER_SATURATION_QUEUE_DEPTH = 8.0
# Scraped engine signals older than this many health-loop periods are
# ignored (treated as neutral) by routing/saturation decisions: a
# replica whose /metrics scrape keeps failing must not be routed on a
# minutes-old queue depth.  Signals with no recorded scrape time (set
# directly by tests or the supervisor) are trusted as fresh.
ROUTER_SIGNAL_STALENESS_FACTOR = 2.0

# -- Replica supervisor (serve/replica_supervisor.py) ----------------
# Crash restarts: jittered exponential backoff between restarts of the
# same replica slot, and how many restarts a slot may consume within
# the rolling window before the supervisor gives the slot up.
SUPERVISOR_RESTART_BASE_DELAY_SECONDS = 1.0
SUPERVISOR_RESTART_MAX_DELAY_SECONDS = 30.0
SUPERVISOR_RESTART_BUDGET = 5
SUPERVISOR_RESTART_WINDOW_SECONDS = 300.0
# Supervisor reconcile cadence (process liveness + autoscaler).
SUPERVISOR_TICK_SECONDS = 1.0
# Scale-down drains before kill: how long to wait for in-flight
# requests to finish after POST /drain before SIGTERM.
SUPERVISOR_DRAIN_TIMEOUT_SECONDS = 60.0

# -- Metrics-driven autoscaling (EngineSignalsAutoscaler) ------------
# Scale up when the fleet's mean decode queue depth per routable
# replica exceeds this...
AUTOSCALE_QUEUE_HIGH = 4.0
# ...and scale down when it stays below this (with >min replicas).
AUTOSCALE_QUEUE_LOW = 0.5
# Consecutive over/under-threshold evaluations before acting
# (hysteresis: one burst must not thrash the fleet).
AUTOSCALE_UPSCALE_PATIENCE = 2
AUTOSCALE_DOWNSCALE_PATIENCE = 5
