"""Per-service bootstrap: controller + load balancer.

Counterpart of the reference's sky/serve/service.py:133 `_start`: for
one service, start the controller (autoscaler + replica manager) and the
load balancer, then supervise until terminated.  The reference runs
these as separate OS processes on a controller VM; here both live in one
service process (threads), started detached by `serve.core.up` — or
in-process for hermetic tests.
"""
from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
from typing import Optional

import yaml

from skypilot_tpu import sky_logging
from skypilot_tpu.serve import constants
from skypilot_tpu.serve import controller as controller_lib
from skypilot_tpu.serve import load_balancer as lb_lib
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve import service_spec as spec_lib

logger = sky_logging.init_logger(__name__)


class ServiceRuntime:
    """The controller + LB pair for one service."""

    def __init__(self, service_name: str,
                 autoscaler_interval_seconds: Optional[float] = None,
                 probe_interval_seconds: Optional[float] = None,
                 lb_sync_interval_seconds: Optional[float] = None) -> None:
        record = serve_state.get_service(service_name)
        if record is None:
            raise ValueError(f'Service {service_name!r} not in state DB.')
        self.service_name = service_name
        self.record = record
        spec = spec_lib.SkyServiceSpec.from_yaml_config(
            yaml.safe_load(record['spec_yaml']))
        self.controller = controller_lib.SkyServeController(
            service_name, spec, record['task_yaml_path'],
            port=record['controller_port'],
            autoscaler_interval_seconds=(autoscaler_interval_seconds or
                                         constants
                                         .AUTOSCALER_INTERVAL_SECONDS),
            probe_interval_seconds=(probe_interval_seconds or
                                    constants.PROBE_INTERVAL_SECONDS))
        # The request-hold on an empty replica set exists ONLY for
        # scale-to-zero wakes; ordinary services (provisioning or in
        # outage) must keep fast-failing 503.  The hold must cover the
        # cold start, which the spec itself estimates via the
        # readiness probe's initial delay.
        wake_wait = 0.0
        if spec.min_replicas == 0:
            wake_wait = max(constants.LB_SCALE_FROM_ZERO_WAIT_SECONDS,
                            spec.initial_delay_seconds)
        self.load_balancer = lb_lib.SkyServeLoadBalancer(
            controller_url=f'http://127.0.0.1:{record["controller_port"]}',
            port=record['load_balancer_port'],
            policy_name=record['policy'],
            sync_interval_seconds=(lb_sync_interval_seconds or
                                   constants.LB_SYNC_INTERVAL_SECONDS),
            scale_from_zero_wait_seconds=wake_wait)

    def start(self) -> None:
        self.controller.start()
        self.load_balancer.start()
        serve_state.set_service_status(
            self.service_name, serve_state.ServiceStatus.REPLICA_INIT)

    def stop(self, terminate_replicas: bool = True) -> None:
        self.load_balancer.stop()
        self.controller.stop(terminate_replicas=terminate_replicas)
        if terminate_replicas:
            serve_state.remove_service(self.service_name)


def _env_interval(name: str) -> Optional[float]:
    raw = os.environ.get(name)
    return float(raw) if raw else None


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--service-name', required=True)
    args = parser.parse_args()
    # Detached-runtime analogue of the kwargs core.up(mode='inline')
    # honors: operational (and test) knobs for the control loops, since
    # a process runtime has no kwargs channel.
    runtime = ServiceRuntime(
        args.service_name,
        autoscaler_interval_seconds=_env_interval(
            'SKYTPU_SERVE_AUTOSCALER_INTERVAL_SECONDS'),
        probe_interval_seconds=_env_interval(
            'SKYTPU_SERVE_PROBE_INTERVAL_SECONDS'),
        lb_sync_interval_seconds=_env_interval(
            'SKYTPU_SERVE_LB_SYNC_INTERVAL_SECONDS'))
    serve_state.set_service_controller_pid(args.service_name, os.getpid())
    stop_event = threading.Event()

    def _on_term(signum, frame):  # pylint: disable=unused-argument
        logger.info(f'Service {args.service_name}: received signal '
                    f'{signum}; terminating replicas.')
        runtime.stop(terminate_replicas=True)
        stop_event.set()

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    runtime.start()
    while not stop_event.is_set():
        stop_event.wait(1.0)
    sys.exit(0)


if __name__ == '__main__':
    main()
