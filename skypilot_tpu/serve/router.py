"""Self-healing serving router: health-driven failover over N replicas.

The serving data plane in front of N ``infer.server.InferenceServer``
replicas.  Where ``serve/load_balancer.py`` TCP-probes blindly and has
never met the inference engine, this router leans on the replica-side
failure containment PR 7 built for it:

* **Health view** — a background loop polls every replica's
  ``GET /health`` (three-state: ok / draining / unhealthy; only *ok* is
  routable) and scrapes its ``/metrics`` for engine-native load signals
  (queue depth, free KV pages, TTFT p99) so routing and autoscaling run
  on what the engine actually feels, not generic QPS.
* **Failover** — connection errors and 503 sheds retry on another
  replica under a per-request budget built on
  ``utils/retry.retry_with_backoff`` (a shed's ``Retry-After`` floors
  the inter-round nap).  The idempotency rule: a request is never
  retried once ANY response byte reached the client — a replica may
  re-execute a request the client never saw tokens from, but a stream
  the client started reading is unrecoverable and aborts instead.
* **Circuit breakers** — per-replica: ``failure_threshold`` consecutive
  delivery failures open the circuit (unroutable), a cooldown later it
  goes half-open, and the next health probe (or request) through it
  closes it again — a flapping replica cannot eat every request's
  retry budget.
* **Prefix affinity** — requests route by the page-chain routing key
  from ``infer/paging.py`` via rendezvous hashing, so prompts sharing
  a page-aligned prefix land on the replica already holding those
  prefix pages; the affine replica is skipped when unroutable or
  saturated (deep queue / no free pages) and the request falls back to
  least-loaded.

Chaos fault points (``utils/chaos.py``): ``slow_replica`` stalls the
forward path, ``proxy_disconnect`` drops the upstream connection after
connect — both land on the retry path, making failover provable in
tier-1 without a real wedged host.

Stdlib-only, same as the rest of the serve stack.  The replica set is
dynamic: ``set_replicas()`` reconciles (the supervisor calls it on
scale events), keeping breaker/health state for surviving URLs.
"""
from __future__ import annotations

import http.client
import http.server
import json
import os
import re
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
import uuid
from typing import Dict, List, Optional, Set

from skypilot_tpu import sky_logging
from skypilot_tpu.infer import handoff as handoff_lib
from skypilot_tpu.infer import paging
from skypilot_tpu.observability import events as events_lib
from skypilot_tpu.observability import ledger as ledger_lib
from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.observability import tracing as tracing_lib
from skypilot_tpu.serve import constants
from skypilot_tpu.utils import chaos
from skypilot_tpu.utils import http_utils
from skypilot_tpu.utils import retry as retry_lib

logger = sky_logging.init_logger(__name__)

_HOP_HEADERS = {'connection', 'keep-alive', 'proxy-authenticate',
                'proxy-authorization', 'te', 'trailers',
                'transfer-encoding', 'upgrade', 'host', 'content-length'}

# Replica status codes the router retries on another replica.  503 is
# handled separately (it is backpressure, not failure — it never trips
# the breaker, and its Retry-After paces the next round); 504 is the
# replica saying the request's own deadline died, so a retry would
# only double-spend a dead budget; 4xx are the client's problem.
_RETRYABLE_REPLICA_CODES = (500, 502)

_PROXY_ROUTES = ('/generate', '/v1/completions', '/v1/chat/completions')

# GET surface, for the wrong-method 405+Allow guard in do_POST (the
# stdlib default answer would be a bare 501, which failover
# classifiers read as a server bug rather than a caller bug).
_GET_ROUTES = ('/health', '/metrics', '/fleet/metrics', '/fleet/slo',
               '/fleet/profile', '/events', '/traces',
               '/router/replicas', '/v1/models')


def _router_metrics(registry: Optional[metrics_lib.Registry] = None):
    """Get-or-create the skytpu_router_* series (all names are in
    observability.METRIC_CONTRACT)."""
    r = registry if registry is not None else metrics_lib.get_registry()
    return {
        'requests': r.counter(
            'skytpu_router_requests_total',
            'Requests through the router, by terminal outcome.',
            labelnames=('outcome',)),
        'latency': r.histogram(
            'skytpu_router_request_seconds',
            'Wall-clock seconds per routed request (all attempts).'),
        'retries': r.counter(
            'skytpu_router_retries_total',
            'Per-attempt failovers/retries, by reason.',
            labelnames=('reason',)),
        'failovers': r.counter(
            'skytpu_router_failovers_total',
            'Requests that completed on a replica other than the '
            'first one attempted.'),
        'affinity': r.counter(
            'skytpu_router_affinity_total',
            'Prefix-affinity routing decisions: hit = routed to the '
            'affine replica, miss = affine replica unroutable or '
            'saturated, none = request carried no routing key.',
            labelnames=('result',)),
        'routable': r.gauge(
            'skytpu_router_replicas_routable',
            'Replicas the router would currently route to (health ok '
            'and circuit not open).'),
        'replicas': r.gauge(
            'skytpu_router_replicas_total',
            'Replicas in the routing table regardless of health.'),
        'probes': r.counter(
            'skytpu_router_health_probes_total',
            'Health-loop probe results, by observed state.',
            labelnames=('result',)),
        'circuit': r.counter(
            'skytpu_router_circuit_transitions_total',
            'Circuit-breaker state transitions, by new state.',
            labelnames=('state',)),
        'signal_age': r.gauge(
            'skytpu_router_signal_age_seconds',
            'Seconds since each replica\'s engine signals (queue '
            'depth, free pages) were last scraped successfully; '
            'signals older than ROUTER_SIGNAL_STALENESS_FACTOR '
            'health-loop periods are ignored by routing.',
            labelnames=('replica',)),
        # Fleet federation (GET /fleet/metrics + /fleet/slo).
        'fleet_routable': r.gauge(
            'skytpu_fleet_replicas_routable',
            'Routable replicas at the last federated scrape.'),
        'fleet_free_pages': r.gauge(
            'skytpu_fleet_free_pages',
            'Sum of free KV pages across routable replicas at the '
            'last federated scrape.'),
        'fleet_queue_depth': r.gauge(
            'skytpu_fleet_queue_depth',
            'Sum of decode queue depths across routable replicas at '
            'the last federated scrape.'),
        'fleet_scrape': r.histogram(
            'skytpu_fleet_scrape_seconds',
            'Wall seconds for one federated scrape of every routable '
            'replica.'),
        'slo_burn': r.gauge(
            'skytpu_slo_burn_rate',
            'Fleet SLO burn rate: violated fraction over the allowed '
            'violation budget (1 = burning exactly the budget).',
            labelnames=('slo',)),
    }


def _goodput_target_from_env() -> float:
    """Fleet goodput target in (0, 1) from SKYTPU_SLO_GOODPUT_TARGET;
    defaults to 0.99 (a 1% violation budget)."""
    try:
        v = float(os.environ.get('SKYTPU_SLO_GOODPUT_TARGET', '')
                  or 0.99)
    except ValueError:
        return 0.99
    return v if 0.0 < v < 1.0 else 0.99


class CircuitBreaker:
    """Per-replica circuit breaker: closed -> open after
    ``failure_threshold`` consecutive failures, open -> half-open after
    ``cooldown_s``, half-open -> closed on the first success (probe or
    request) and back to open on the first failure.

    Thread-safe: handler threads and the health loop both touch it.
    ``state`` is evaluated lazily so no timer thread is needed.
    """

    CLOSED = 'closed'
    OPEN = 'open'
    HALF_OPEN = 'half_open'

    def __init__(self, failure_threshold: int =
                 constants.ROUTER_CB_FAILURE_THRESHOLD,
                 cooldown_s: float = constants.ROUTER_CB_COOLDOWN_SECONDS,
                 clock=time.monotonic, on_transition=None):
        if failure_threshold < 1:
            raise ValueError('failure_threshold must be >= 1, got '
                             f'{failure_threshold}')
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._consecutive_failures = 0
        self._opened_at: Optional[float] = None

    def _transition(self, state: str) -> None:
        self._state = state
        if self._on_transition is not None:
            self._on_transition(state)

    def _evaluate(self) -> str:
        if self._state == self.OPEN and \
                self._clock() - self._opened_at >= self.cooldown_s:
            self._transition(self.HALF_OPEN)
        return self._state

    @property
    def state(self) -> str:
        with self._lock:
            return self._evaluate()

    @property
    def allows_requests(self) -> bool:
        """False only while OPEN (half-open lets a trial through — its
        outcome closes or reopens the circuit)."""
        return self.state != self.OPEN

    def record_success(self) -> None:
        with self._lock:
            self._evaluate()
            self._consecutive_failures = 0
            if self._state != self.CLOSED:
                self._transition(self.CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            state = self._evaluate()
            if state == self.HALF_OPEN:
                # The trial failed: straight back to open, new cooldown.
                self._opened_at = self._clock()
                self._consecutive_failures = self.failure_threshold
                self._transition(self.OPEN)
                return
            self._consecutive_failures += 1
            if state == self.CLOSED and \
                    self._consecutive_failures >= self.failure_threshold:
                self._opened_at = self._clock()
                self._transition(self.OPEN)

    def on_probe(self, ok: bool) -> None:
        """A health-loop probe doubles as the half-open trial: a
        recovered replica is re-admitted without risking a live
        request.  Probes never trip a closed breaker (request-delivery
        failures own that) and never touch an open one (the cooldown
        owns re-entry)."""
        if self.state != self.HALF_OPEN:
            return
        if ok:
            self.record_success()
        else:
            self.record_failure()


class ReplicaView:
    """The router's view of one replica: health, breaker, and the
    engine signals scraped from its /metrics."""

    def __init__(self, url: str, breaker: Optional[CircuitBreaker] = None):
        self.url = url.rstrip('/')
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        self.health = 'unknown'    # ok | draining | unhealthy | unreachable
        self.inflight = 0          # router-side live proxied requests
        self.queue_depth = 0.0     # skytpu_decode_queue_depth
        self.free_pages: Optional[float] = None  # skytpu_kv_free_pages
        self.ttft_p99_s: Optional[float] = None  # from TTFT histogram
        self.page_size: Optional[int] = None     # from /health?verbose=1
        self.role = 'both'         # both | prefill | decode (verbose /health)
        # monotonic ts of the last SUCCESSFUL /metrics scrape; None
        # means "never stamped" and is trusted as fresh (tests and the
        # supervisor set signal fields directly).
        self.signals_at: Optional[float] = None
        self.consecutive_probe_failures = 0

    @property
    def routable(self) -> bool:
        return self.health == 'ok' and self.breaker.allows_requests

    def signal_age_s(self) -> Optional[float]:
        if self.signals_at is None:
            return None
        return time.monotonic() - self.signals_at

    def snapshot(self) -> Dict[str, object]:
        age = self.signal_age_s()
        return {'url': self.url, 'health': self.health,
                'circuit': self.breaker.state,
                'role': self.role,
                'inflight': self.inflight,
                'queue_depth': self.queue_depth,
                'free_pages': self.free_pages,
                'ttft_p99_s': self.ttft_p99_s,
                'signal_age_s': (round(age, 3)
                                 if age is not None else None),
                'routable': self.routable}


class _RoundExhausted(Exception):
    """Every candidate replica in one failover round failed or shed.
    ``retry_after_s`` (the smallest Retry-After any shed named) floors
    the nap retry_with_backoff takes before the next round."""

    def __init__(self, message: str, retry_after_s: Optional[float] = None):
        super().__init__(message)
        if retry_after_s is not None:
            self.retry_after_s = retry_after_s


def _parse_retry_after(headers) -> Optional[float]:
    raw = headers.get('Retry-After') if headers is not None else None
    if raw is None:
        return None
    try:
        return min(max(float(raw), 0.0), 60.0)
    except (TypeError, ValueError):
        return None


def extract_routing_key(path: str, body: Optional[bytes],
                        page_size: int) -> Optional[int]:
    """Routing key for prefix affinity, or None (no affinity).

    ``/generate`` keys on the page-chain hash of the first prompt's
    token ids — the exact chain ``infer/paging.py`` uses for prefix-
    page sharing, so affinity aligns with what the replica's prefix
    cache can actually reuse.  The OpenAI text routes key on the
    prompt text's leading bytes at page-size granularity (tokenization
    happens replica-side; byte-prefix equality is a conservative
    stand-in for token-prefix equality).  Malformed bodies yield None:
    the router stays thin and lets the replica produce the 400.
    """
    if body is None or not path:
        return None
    try:
        payload = json.loads(body)
    except (ValueError, UnicodeDecodeError):
        return None
    if not isinstance(payload, dict):
        return None
    if path == '/generate':
        prompts = payload.get('prompt_ids')
        if (isinstance(prompts, list) and prompts
                and isinstance(prompts[0], list) and prompts[0]):
            try:
                return paging.routing_key(
                    [int(t) for t in prompts[0]], page_size)
            except (TypeError, ValueError):
                return None
        return None
    if path == '/v1/completions':
        text = payload.get('prompt')
    elif path == '/v1/chat/completions':
        messages = payload.get('messages')
        if not isinstance(messages, list):
            return None
        text = json.dumps(messages, sort_keys=True)
    else:
        return None
    if not isinstance(text, str) or not text:
        return None
    # ~4 bytes/token keeps byte-prefix granularity near page
    # granularity; chain_hashes needs an int sequence.
    return paging.routing_key(list(text.encode()), page_size * 4)


class Router:
    """HTTP front-end + health loop.  See the module docstring for the
    routing/failover contract."""

    def __init__(self, replicas: Optional[List[str]] = None,
                 port: int = 0, host: str = '127.0.0.1',
                 health_interval_s: float =
                 constants.ROUTER_HEALTH_INTERVAL_SECONDS,
                 health_timeout_s: float =
                 constants.ROUTER_HEALTH_TIMEOUT_SECONDS,
                 attempt_timeout_s: float =
                 constants.ROUTER_ATTEMPT_TIMEOUT_SECONDS,
                 request_budget_s: float =
                 constants.ROUTER_REQUEST_BUDGET_SECONDS,
                 max_rounds: int = constants.ROUTER_MAX_ROUNDS,
                 affinity_page_size: int =
                 constants.ROUTER_AFFINITY_PAGE_SIZE,
                 saturation_queue_depth: float =
                 constants.ROUTER_SATURATION_QUEUE_DEPTH,
                 failure_threshold: int =
                 constants.ROUTER_CB_FAILURE_THRESHOLD,
                 cooldown_s: float = constants.ROUTER_CB_COOLDOWN_SECONDS,
                 registry: Optional[metrics_lib.Registry] = None):
        self._host = host
        self._port = port
        self.health_interval_s = health_interval_s
        self.health_timeout_s = health_timeout_s
        self.attempt_timeout_s = attempt_timeout_s
        self.request_budget_s = request_budget_s
        self.max_rounds = max_rounds
        self.affinity_page_size = affinity_page_size
        self.saturation_queue_depth = saturation_queue_depth
        self._failure_threshold = failure_threshold
        self._cooldown_s = cooldown_s
        self._met = _router_metrics(registry)
        self.registry = (registry if registry is not None
                         else metrics_lib.get_registry())
        # Router-side distributed tracing: one root span per proxied
        # request + one child span per delivery attempt, keyed by the
        # external X-Request-Id (GET /traces serves these).
        self.spans = tracing_lib.SpanStore()
        # Flight recorder (GET /events): breaker transitions, health
        # flips, and — via the supervisor wiring — restarts/drains/
        # scale decisions land here.
        self.events = events_lib.EventRing(registry=self.registry,
                                           source='router')
        chaos.add_event_sink(self._record_chaos_event)
        # SLO goodput target for burn-rate math (SRE convention:
        # burn rate 1.0 = violating exactly the allowed budget).
        self.slo_goodput_target = _goodput_target_from_env()
        self._lock = threading.Lock()
        self._replicas: Dict[str, ReplicaView] = {}
        self._server: Optional[http.server.ThreadingHTTPServer] = None
        self._stop_evt = threading.Event()
        self._health_thread: Optional[threading.Thread] = None
        if replicas:
            self.set_replicas(replicas)

    # -- replica set --------------------------------------------------
    def _record_chaos_event(self, point: str) -> None:
        self.events.record('chaos_injection', point=point)

    def _new_view(self, url: str) -> ReplicaView:
        def _on_transition(state: str, url: str = url) -> None:
            self._met['circuit'].labels(state=state).inc()
            self.events.record('breaker_transition', url=url,
                               state=state)
        return ReplicaView(url, CircuitBreaker(
            failure_threshold=self._failure_threshold,
            cooldown_s=self._cooldown_s,
            on_transition=_on_transition))

    def set_replicas(self, urls: List[str]) -> None:
        """Reconcile the routing table; existing views (health +
        breaker history) survive for URLs that stay."""
        with self._lock:
            keep = {u.rstrip('/') for u in urls}
            for url in list(self._replicas):
                if url not in keep:
                    del self._replicas[url]
            for url in keep:
                if url not in self._replicas:
                    self._replicas[url] = self._new_view(url)
        self._publish_replica_gauges()

    def add_replica(self, url: str) -> None:
        with self._lock:
            url = url.rstrip('/')
            if url not in self._replicas:
                self._replicas[url] = self._new_view(url)
        self._publish_replica_gauges()

    def remove_replica(self, url: str) -> None:
        with self._lock:
            self._replicas.pop(url.rstrip('/'), None)
        self._publish_replica_gauges()

    def mark_draining(self, url: str) -> None:
        """Supervisor handshake: stop routing to a replica that is
        about to be drained without waiting for the next probe."""
        with self._lock:
            view = self._replicas.get(url.rstrip('/'))
            if view is not None:
                view.health = 'draining'
        self._publish_replica_gauges()

    def views(self) -> List[ReplicaView]:
        with self._lock:
            return list(self._replicas.values())

    def _publish_replica_gauges(self) -> None:
        views = self.views()
        self._met['replicas'].set(len(views))
        self._met['routable'].set(
            sum(1 for v in views if v.routable))

    # -- health loop --------------------------------------------------
    def _probe_replica(self, view: ReplicaView) -> str:
        """One GET /health round trip -> observed state string."""
        try:
            resp = urllib.request.urlopen(
                view.url + '/health', timeout=self.health_timeout_s)
            with resp:
                body = json.loads(resp.read() or b'{}')
            return body.get('status', 'ok') if isinstance(body, dict) \
                else 'ok'
        except urllib.error.HTTPError as e:
            # Three-state contract: 503 carries draining/unhealthy in
            # the body.  An unreadable body is 'unhealthy' (the replica
            # answered 503 but could not say why).
            try:
                body = json.loads(e.read() or b'{}')
            except ValueError:
                body = {}
            finally:
                e.close()
            status = body.get('status') if isinstance(body, dict) else None
            return status if status in ('draining', 'unhealthy') \
                else 'unhealthy'
        except (urllib.error.URLError, ConnectionError, TimeoutError,
                OSError, http.client.HTTPException, ValueError):
            return 'unreachable'

    def _scrape_signals(self, view: ReplicaView) -> None:
        try:
            resp = urllib.request.urlopen(
                view.url + '/metrics', timeout=self.health_timeout_s)
            with resp:
                parsed = metrics_lib.parse_exposition(
                    resp.read().decode('utf-8', 'replace'))
        except (urllib.error.URLError, urllib.error.HTTPError,
                ConnectionError, TimeoutError, OSError,
                http.client.HTTPException, ValueError):
            return  # stale signals beat no routing at all
        depth = metrics_lib.sample_value(
            parsed, 'skytpu_decode_queue_depth')
        if depth is not None:
            view.queue_depth = depth
        view.free_pages = metrics_lib.sample_value(
            parsed, 'skytpu_kv_free_pages')
        view.ttft_p99_s = metrics_lib.histogram_quantile(
            parsed, 'skytpu_request_ttft_seconds', 0.99)
        # Stamp the scrape time: routing trusts these signals only
        # while they are younger than the staleness window.
        view.signals_at = time.monotonic()

    def _fetch_page_size(self, view: ReplicaView) -> None:
        if view.page_size is not None:
            return
        try:
            resp = urllib.request.urlopen(
                view.url + '/health?verbose=1',
                timeout=self.health_timeout_s)
            with resp:
                body = json.loads(resp.read() or b'{}')
        except (urllib.error.URLError, urllib.error.HTTPError,
                ConnectionError, TimeoutError, OSError,
                http.client.HTTPException, ValueError):
            return
        # Role discovery rides the same verbose probe: a prefill-role
        # replica gets client traffic plus a decode target; a
        # decode-role replica gets /handoff traffic only.
        role = body.get('role') if isinstance(body, dict) else None
        if role in ('both', 'prefill', 'decode'):
            view.role = role
        ps = body.get('page_size') if isinstance(body, dict) else None
        if isinstance(ps, int) and ps > 0:
            view.page_size = ps
            # Align affinity granularity with the replicas' actual
            # prefix-cache page size (first reporter wins; a mixed
            # fleet keeps the configured default).
            with self._lock:
                sizes = {v.page_size for v in self._replicas.values()
                         if v.page_size}
                if len(sizes) == 1:
                    self.affinity_page_size = sizes.pop()

    def health_tick(self) -> None:
        """One pass over every replica: probe /health, feed the
        breaker's half-open trial, scrape /metrics signals.  Public so
        tests (and the supervisor) can drive it synchronously."""
        for view in self.views():
            status = self._probe_replica(view)
            self._met['probes'].labels(result=status).inc()
            if status == 'ok':
                view.consecutive_probe_failures = 0
                view.health = 'ok'
                view.breaker.on_probe(True)
                self._fetch_page_size(view)
                self._scrape_signals(view)
            else:
                view.consecutive_probe_failures += 1
                prev = view.health
                view.health = status
                view.breaker.on_probe(False)
                if status in ('unhealthy', 'unreachable') and \
                        prev not in ('unhealthy', 'unreachable'):
                    self.events.record('replica_unhealthy',
                                       url=view.url, status=status)
            age = view.signal_age_s()
            if age is not None:
                self._met['signal_age'].labels(
                    replica=view.url).set(age)
        self._publish_replica_gauges()

    def _health_loop(self) -> None:
        while not self._stop_evt.wait(self.health_interval_s):
            try:
                self.health_tick()
            except Exception:  # pylint: disable=broad-except
                logger.exception('router health tick failed')

    # -- fleet federation ---------------------------------------------
    _SCRAPE_ERRORS = (urllib.error.URLError, urllib.error.HTTPError,
                      ConnectionError, TimeoutError, OSError,
                      http.client.HTTPException, ValueError)

    def _scrape_exposition(self, view: ReplicaView):
        """One replica's parsed /metrics, or None (scrape failure is a
        data gap, not an error — the replica may have just died)."""
        try:
            resp = urllib.request.urlopen(
                view.url + '/metrics', timeout=self.health_timeout_s)
            with resp:
                return metrics_lib.parse_exposition(
                    resp.read().decode('utf-8', 'replace'))
        except self._SCRAPE_ERRORS:
            return None

    def fleet_metrics(self) -> str:
        """Federated exposition: every routable replica's samples
        re-rendered with a ``replica`` label, plus the fleet-level
        gauges.  The output round-trips through parse_exposition."""
        t0 = time.perf_counter()
        lines: List[str] = []
        routable = [v for v in self.views() if v.routable]
        fleet_free = 0.0
        fleet_queue = 0.0
        for view in sorted(routable, key=lambda v: v.url):
            parsed = self._scrape_exposition(view)
            if parsed is None:
                continue
            fleet_free += metrics_lib.sample_value(
                parsed, 'skytpu_kv_free_pages') or 0.0
            fleet_queue += metrics_lib.sample_value(
                parsed, 'skytpu_decode_queue_depth') or 0.0
            esc = metrics_lib._escape_label_value(view.url)
            role = metrics_lib._escape_label_value(view.role)
            for name in sorted(parsed):
                for labels, value in sorted(parsed[name].items()):
                    pairs = [f'replica="{esc}"', f'role="{role}"'] + [
                        f'{k}="{metrics_lib._escape_label_value(v)}"'
                        for k, v in labels]
                    lines.append(
                        f'{name}{{{",".join(pairs)}}} '
                        f'{metrics_lib._fmt_value(value)}')
        self._met['fleet_routable'].set(len(routable))
        self._met['fleet_free_pages'].set(fleet_free)
        self._met['fleet_queue_depth'].set(fleet_queue)
        lines.append(f'skytpu_fleet_replicas_routable {len(routable)}')
        lines.append('skytpu_fleet_free_pages '
                     f'{metrics_lib._fmt_value(fleet_free)}')
        lines.append('skytpu_fleet_queue_depth '
                     f'{metrics_lib._fmt_value(fleet_queue)}')
        self._met['fleet_scrape'].observe(time.perf_counter() - t0)
        return '\n'.join(lines) + '\n'

    def fleet_slo(self) -> Dict[str, object]:
        """Fleet SLO account: sums each replica's
        skytpu_slo_requests_total verdicts, derives per-SLO goodput
        and burn rate (violated fraction over the violation budget
        ``1 - goodput_target``), and publishes the burn gauges."""
        counts: Dict[str, Dict[str, float]] = {}
        for view in self.views():
            if not view.routable:
                continue
            parsed = self._scrape_exposition(view)
            if not parsed:
                continue
            for labels, value in parsed.get(
                    'skytpu_slo_requests_total', {}).items():
                ld = dict(labels)
                slo = ld.get('slo')
                result = ld.get('result')
                if slo and result:
                    counts.setdefault(slo, {}).setdefault(result, 0.0)
                    counts[slo][result] += value
        budget = 1.0 - self.slo_goodput_target
        slos: Dict[str, object] = {}
        for slo, by_result in sorted(counts.items()):
            good = by_result.get('good', 0.0)
            violated = by_result.get('violated', 0.0)
            total = good + violated
            goodput = good / total if total else None
            violated_frac = violated / total if total else 0.0
            burn = violated_frac / budget
            self._met['slo_burn'].labels(slo=slo).set(burn)
            slos[slo] = {'good': good, 'violated': violated,
                         'goodput': goodput, 'burn_rate': burn}
        return {'goodput_target': self.slo_goodput_target,
                'slos': slos}

    def stitch_trace(self, trace_id: str) -> List[Dict[str, object]]:
        """Replica-side engine timelines for one external request id:
        each replica's /traces filtered to that http request id.
        Unreachable replicas (e.g. the corpse a failover routed
        around) contribute nothing — the router-side attempt spans
        already tell that part of the story."""
        out: List[Dict[str, object]] = []
        q = urllib.parse.urlencode({'request_id': trace_id})
        for view in sorted(self.views(), key=lambda v: v.url):
            try:
                resp = urllib.request.urlopen(
                    f'{view.url}/traces?{q}',
                    timeout=self.health_timeout_s)
                with resp:
                    body = json.loads(resp.read() or b'{}')
            except self._SCRAPE_ERRORS:
                continue
            traces = body.get('traces') if isinstance(body, dict) \
                else None
            if traces:
                out.append({'replica': view.url, 'traces': traces})
        return out

    def fleet_profile(self, limit: int = 256) -> Dict[str, object]:
        """Fleet performance roll-up: each routable replica's recent
        step-ledger window (`GET /profile/steps`) summarized to
        achieved MFU, step-time p50/p99, tokens/sec and the roofline
        verdict mix — the dashboard's MFU/step-p99 columns and the
        first place to look when one replica's goodput sags.
        Unreachable replicas contribute nothing (data gap, like
        fleet_metrics)."""
        replicas: List[Dict[str, object]] = []
        q = urllib.parse.urlencode({'limit': limit})
        for view in sorted(self.views(), key=lambda v: v.url):
            if not view.routable:
                continue
            try:
                resp = urllib.request.urlopen(
                    f'{view.url}/profile/steps?{q}',
                    timeout=self.health_timeout_s)
                with resp:
                    body = json.loads(resp.read() or b'{}')
            except self._SCRAPE_ERRORS:
                continue
            steps = body.get('steps') if isinstance(body, dict) \
                else None
            if steps is None:
                continue
            entry: Dict[str, object] = {
                'replica': view.url,
                'role': view.role,
                **ledger_lib.summarize_steps(steps),
            }
            info = body.get('info')
            if isinstance(info, dict):
                # Static roofline model facts worth surfacing next to
                # the window summary.
                for key in ('model', 'device_kind', 'n_chips',
                            'peak_tflops', 'ridge_flops_per_byte',
                            'enabled'):
                    if key in info:
                        entry[key] = info[key]
            replicas.append(entry)
        mfus = [r['achieved_mfu'] for r in replicas
                if r.get('achieved_mfu') is not None]
        return {
            'replicas': replicas,
            'fleet_mfu': (sum(mfus) / len(mfus)) if mfus else None,
        }

    # -- selection ----------------------------------------------------
    def _signals(self, view: ReplicaView):
        """(queue_depth, free_pages) with staleness applied: signals
        scraped more than ROUTER_SIGNAL_STALENESS_FACTOR health-loop
        periods ago are replaced by neutral values — routing on a
        minutes-old queue depth is worse than routing blind.  An
        unstamped view (signals set directly, never scraped) is
        trusted as-is."""
        age = view.signal_age_s()
        if age is not None and age > (
                constants.ROUTER_SIGNAL_STALENESS_FACTOR
                * self.health_interval_s):
            return 0.0, None
        return view.queue_depth, view.free_pages

    def _saturated(self, view: ReplicaView) -> bool:
        queue_depth, free_pages = self._signals(view)
        if queue_depth >= self.saturation_queue_depth:
            return True
        return free_pages == 0.0 and queue_depth > 0

    def select_replica(self, key: Optional[int],
                       exclude: Optional[Set[str]] = None
                       ) -> Optional[ReplicaView]:
        """Affine replica by rendezvous hash when it is routable and
        unsaturated; least-loaded routable otherwise.  Decode-role
        replicas never take client traffic — they are reached through
        the handoff path only (_select_decode_target)."""
        exclude = exclude or set()
        with self._lock:
            candidates = [v for v in self._replicas.values()
                          if v.routable and v.url not in exclude
                          and v.role in ('both', 'prefill')]
        if not candidates:
            return None
        if key is not None:
            affine = max(candidates,
                         key=lambda v: hash((key, v.url)))
            if not self._saturated(affine):
                self._met['affinity'].labels(result='hit').inc()
                return affine
            self._met['affinity'].labels(result='miss').inc()
        else:
            self._met['affinity'].labels(result='none').inc()
        return min(candidates,
                   key=lambda v: (v.inflight + self._signals(v)[0],
                                  v.url))

    def _prefix_owner(self, key: int) -> Optional[str]:
        """URL of the rendezvous OWNER of an affinity key — the
        replica whose cache tiers most likely hold the prompt's prefix
        pages.  Stamped as X-Skytpu-Prefix-Peer when saturation forced
        routing AWAY from the owner, so the chosen replica can fetch
        the pages over GET /kv_prefix instead of re-prefilling them."""
        with self._lock:
            candidates = [v for v in self._replicas.values()
                          if v.routable
                          and v.role in ('both', 'prefill')]
        if not candidates:
            return None
        return max(candidates, key=lambda v: hash((key, v.url))).url

    def _select_decode_target(self, key: Optional[int]
                              ) -> Optional[ReplicaView]:
        """The decode replica a prefill-role replica should hand off
        to: rendezvous over decode-capable replicas with the SAME
        affinity key client routing uses, so repeated prompts land
        their handoffs where the prefix pages already live (the
        page-id dedupe then ships only the tail).  Pure decode
        replicas are preferred over --role both ones; least-loaded is
        the saturation fallback."""
        with self._lock:
            candidates = [v for v in self._replicas.values()
                          if v.routable
                          and v.role in ('both', 'decode')]
        pool = [v for v in candidates if v.role == 'decode'] \
            or candidates
        if not pool:
            return None
        if key is not None:
            affine = max(pool, key=lambda v: hash((key, v.url)))
            if not self._saturated(affine):
                return affine
        return min(pool,
                   key=lambda v: (v.inflight + self._signals(v)[0],
                                  v.url))

    # -- lifecycle ----------------------------------------------------
    @property
    def port(self) -> int:
        assert self._server is not None
        return self._server.server_address[1]

    @property
    def url(self) -> str:
        return f'http://{self._host}:{self.port}'

    def start(self) -> None:
        self._server = http_utils.HighBacklogHTTPServer(
            (self._host, self._port), self._make_handler())
        # poll_interval: shutdown() blocks until the serve loop's next
        # poll; 50ms keeps stop()/drain latency (and every test
        # teardown) snappy at negligible idle cost.
        threading.Thread(
            target=lambda: self._server.serve_forever(
                poll_interval=0.05),
            daemon=True, name='skytpu-router-http').start()
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True,
            name='skytpu-router-health')
        self._health_thread.start()
        logger.info(f'router on :{self.port} over '
                    f'{len(self.views())} replica(s)')

    def stop(self) -> None:
        self._stop_evt.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout=5)
            self._health_thread = None
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    # -- proxy --------------------------------------------------------
    def _make_handler(self):
        router = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = 'HTTP/1.1'
            request_id = '-'

            def log_message(self, format, *args):  # noqa: A002
                logger.debug(f'{self.address_string()} '
                             f'[{self.request_id}] {format % args}')

            def _reply(self, code: int, body: dict,
                       retry_after: Optional[float] = None,
                       allow: Optional[str] = None) -> None:
                data = json.dumps(body).encode()
                try:
                    self.send_response(code)
                    self.send_header('X-Request-Id', self.request_id)
                    self.send_header('Content-Type', 'application/json')
                    self.send_header('Content-Length', str(len(data)))
                    if retry_after is not None:
                        self.send_header(
                            'Retry-After', str(max(1, int(retry_after))))
                    if allow is not None:
                        self.send_header('Allow', allow)
                    self.end_headers()
                    self.wfile.write(data)
                except OSError:
                    self.close_connection = True

            def _send_text(self, data: bytes, content_type: str) -> None:
                try:
                    self.send_response(200)
                    self.send_header('Content-Type', content_type)
                    self.send_header('Content-Length', str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                except OSError:
                    self.close_connection = True

            def do_GET(self):  # noqa: N802
                route, _, query = self.path.partition('?')
                params = urllib.parse.parse_qs(query)
                self.request_id = router._request_id(self.headers)
                if route == '/health':
                    views = router.views()
                    routable = sum(1 for v in views if v.routable)
                    code = 200 if routable else 503
                    self._reply(code, {
                        'status': 'ok' if routable else 'unhealthy',
                        'replicas': len(views),
                        'routable': routable})
                elif route == '/metrics':
                    self._send_text(router.registry.expose().encode(),
                                    metrics_lib.CONTENT_TYPE_LATEST)
                elif route == '/fleet/metrics':
                    self._send_text(router.fleet_metrics().encode(),
                                    metrics_lib.CONTENT_TYPE_LATEST)
                elif route == '/fleet/slo':
                    self._reply(200, router.fleet_slo())
                elif route == '/fleet/profile':
                    try:
                        limit = int(params.get('limit', ['256'])[0])
                    except ValueError:
                        limit = 256
                    self._reply(200, router.fleet_profile(limit))
                elif route == '/events':
                    try:
                        limit = int(params.get('limit', ['100'])[0])
                    except ValueError:
                        limit = 100
                    self._reply(200, {
                        'events': router.events.snapshot(limit)})
                elif route == '/traces':
                    try:
                        limit = int(params.get('limit', ['50'])[0])
                    except ValueError:
                        limit = 50
                    trace_id = (params.get('id') or [None])[0]
                    if trace_id is None:
                        self._reply(200,
                                    {'traces': router.spans.recent(limit)})
                    else:
                        doc = {'trace_id': trace_id,
                               'spans': router.spans.get(trace_id)}
                        if params.get('stitch', ['0'])[0] not in (
                                '0', '', 'false'):
                            doc['replica_traces'] = \
                                router.stitch_trace(trace_id)
                        self._reply(200, doc)
                elif route == '/router/replicas':
                    self._reply(200, {
                        'replicas': [v.snapshot()
                                     for v in router.views()]})
                elif route == '/v1/models':
                    router._proxy(self, body=None)
                elif route in _PROXY_ROUTES:
                    self._reply(405, {'error': 'method not allowed'},
                                allow='POST')
                else:
                    self._reply(404, {'error': 'not found'})

            def do_POST(self):  # noqa: N802
                route = self.path.split('?', 1)[0]
                self.request_id = router._request_id(self.headers)
                if route not in _PROXY_ROUTES:
                    if route in _GET_ROUTES:
                        self._reply(405,
                                    {'error': 'method not allowed'},
                                    allow='GET')
                    else:
                        self._reply(404, {'error': 'not found'})
                    return
                try:
                    length = int(self.headers.get('Content-Length', 0))
                except ValueError:
                    self._reply(400, {'error': 'bad Content-Length'})
                    return
                body = self.rfile.read(length) if length > 0 else b''
                router._proxy(self, body=body)

        return Handler

    @staticmethod
    def _request_id(headers) -> str:
        incoming = headers.get('X-Request-Id', '')
        if re.fullmatch(r'[A-Za-z0-9._:-]{1,64}', incoming or ''):
            return incoming
        return 'rtr-' + uuid.uuid4().hex[:16]

    def _budget_from(self, body: Optional[bytes]) -> float:
        """The router's failover budget never outlives the request's
        own deadline (retrying a request whose deadline died just
        manufactures 504s)."""
        budget = self.request_budget_s
        if body:
            try:
                payload = json.loads(body)
                deadline = float(payload.get('deadline_s'))
                if deadline > 0:
                    budget = min(budget, deadline)
            except (ValueError, TypeError, AttributeError):
                pass
        return budget

    def _proxy(self, handler, body: Optional[bytes]) -> None:
        path = handler.path
        route = path.split('?', 1)[0]
        key = extract_routing_key(route, body, self.affinity_page_size)
        headers = {k: v for k, v in handler.headers.items()
                   if k.lower() not in _HOP_HEADERS}
        headers['X-Request-Id'] = handler.request_id
        deadline = time.monotonic() + self._budget_from(body)
        # The external request id IS the trace id: every router span,
        # the X-Skytpu-Trace header, and the replica-side engine trace
        # all key off it so GET /traces?id=...&stitch=1 joins them.
        root = self.spans.start(handler.request_id, 'router.request',
                                route=route, affinity_key=key is not None)
        state = {'client_started': False, 'attempts': 0,
                 'first_url': None, 'served_url': None,
                 'retry_after': None, 'root': root, 'key': key}
        tried: Set[str] = set()
        t0 = time.perf_counter()

        def _one_round():
            state['retry_after'] = None
            progressed = False
            while True:
                view = self.select_replica(key, exclude=tried)
                if view is None:
                    break
                tried.add(view.url)
                progressed = True
                state['attempts'] += 1
                if state['first_url'] is None:
                    state['first_url'] = view.url
                if self._attempt(handler, view, path, body, headers,
                                 state):
                    return
            # Candidates exhausted (or none routable): next round may
            # retry everyone once backoff/Retry-After has elapsed.
            tried.clear()
            raise _RoundExhausted(
                'no replica delivered the request'
                + ('' if progressed else ' (none routable)'),
                retry_after_s=state['retry_after'])

        try:
            retry_lib.retry_with_backoff(
                _one_round,
                max_attempts=self.max_rounds,
                base_delay_s=0.05, max_delay_s=2.0,
                retry_on=(_RoundExhausted,),
                remaining_s=lambda: deadline - time.monotonic(),
                min_attempt_s=0.01,
                describe='router failover')
        except retry_lib.RetryError:
            if not state['client_started']:
                self._met['requests'].labels(outcome='unroutable').inc()
                root.end(status='unroutable',
                         attempts=state['attempts'])
                handler._reply(  # pylint: disable=protected-access
                    503, {'error': 'no routable replica delivered the '
                                   'request within the retry budget',
                          'attempts': state['attempts'],
                          'request_id': handler.request_id},
                    retry_after=state['retry_after'] or 1)
            else:
                self._met['requests'].labels(
                    outcome='aborted_midstream').inc()
                root.end(status='aborted_midstream',
                         attempts=state['attempts'])
            return
        finally:
            self._met['latency'].observe(time.perf_counter() - t0)
        if state['served_url'] is not None and \
                state['served_url'] != state['first_url']:
            self._met['failovers'].inc()
        self._met['requests'].labels(outcome='ok').inc()
        root.end(status='ok', attempts=state['attempts'],
                 served_by=state['served_url'],
                 failover=(state['served_url'] is not None
                           and state['served_url'] != state['first_url']))

    def _attempt(self, handler, view: ReplicaView, path: str,
                 body: Optional[bytes], headers: Dict[str, str],
                 state: dict) -> bool:
        """One delivery attempt.  True = terminal (a response reached
        the client, successfully or not); False = retry on another
        replica.  A False return NEVER follows client-visible bytes —
        that is the no-double-execution rule for streamed requests."""
        chaos.maybe_hang('slow_replica')
        root = state['root']
        span = self.spans.start(root.trace_id, 'router.attempt',
                                parent_id=root.span_id, url=view.url,
                                breaker=view.breaker.state)
        # The attempt span is the replica's parent: its id rides the
        # X-Skytpu-Trace header so the replica's engine trace nests
        # under the exact attempt that reached it (overwritten per
        # attempt in the shared headers dict).
        headers[tracing_lib.TRACE_HEADER] = \
            tracing_lib.format_trace_context(root.trace_id,
                                             span.span_id)
        # Disaggregated serving: a prefill-role replica needs to know
        # where to ship the KV artifact.  The same affinity key drives
        # the pick so a repeated prompt's handoff lands on the decode
        # replica already holding its prefix pages.  Overwritten (or
        # cleared) per attempt in the shared headers dict.
        headers.pop(handoff_lib.DECODE_TARGET_HEADER, None)
        if view.role == 'prefill':
            target = self._select_decode_target(state.get('key'))
            if target is not None:
                headers[handoff_lib.DECODE_TARGET_HEADER] = target.url
        # Fleet prefix-cache tier: when this attempt is NOT going to
        # the key's rendezvous owner (saturation overflow, failover),
        # name the owner so the serving replica can pull the prefix
        # pages it is missing.  Cleared per attempt — an attempt that
        # DOES reach the owner must not fetch from itself.
        headers.pop(handoff_lib.PREFIX_PEER_HEADER, None)
        key = state.get('key')
        if key is not None:
            owner = self._prefix_owner(key)
            if owner is not None and owner != view.url:
                headers[handoff_lib.PREFIX_PEER_HEADER] = owner
        outcome = 'unknown'
        with self._lock:
            view.inflight += 1
        try:
            req = urllib.request.Request(
                view.url + path, data=body, headers=headers,
                method=handler.command)
            try:
                resp = urllib.request.urlopen(
                    req, timeout=self.attempt_timeout_s)
            except urllib.error.HTTPError as e:
                with e:
                    if e.code == 503:
                        ra = _parse_retry_after(e.headers)
                        if ra is not None and (
                                state['retry_after'] is None
                                or ra < state['retry_after']):
                            state['retry_after'] = ra
                        self._met['retries'].labels(
                            reason='shed').inc()
                        outcome = 'shed'
                        return False
                    if e.code in _RETRYABLE_REPLICA_CODES:
                        view.breaker.record_failure()
                        self._met['retries'].labels(
                            reason='replica_5xx').inc()
                        outcome = 'replica_5xx'
                        return False
                    # Deterministic replica answer (4xx, 504): the
                    # client's to see, not the router's to retry.
                    outcome = f'relayed_{e.code}'
                    self._relay(handler, e, view, state)
                    return True
            except (urllib.error.URLError, ConnectionError,
                    TimeoutError, OSError,
                    http.client.HTTPException) as e:
                view.breaker.record_failure()
                self._met['retries'].labels(reason='conn_error').inc()
                outcome = 'conn_error'
                logger.warning(
                    f'replica {view.url} failed ({e!r}); failing over')
                return False
            with resp:
                if chaos.should_inject('proxy_disconnect'):
                    # Upstream dropped after connect, before any client
                    # byte: retryable by the idempotency rule.
                    view.breaker.record_failure()
                    self._met['retries'].labels(
                        reason='conn_error').inc()
                    outcome = 'proxy_disconnect'
                    return False
                view.breaker.record_success()
                state['served_url'] = view.url
                outcome = 'relayed'
                self._relay(handler, resp, view, state)
            return True
        finally:
            span.end(status='ok' if outcome.startswith('relayed')
                     else 'retry', outcome=outcome)
            with self._lock:
                view.inflight -= 1

    def _relay(self, handler, resp, view: ReplicaView,
               state: dict) -> None:
        """Stream the replica response to the client in chunks (SSE
        reaches the client incrementally).  The first byte here makes
        the request non-retryable; mid-relay failures close the client
        connection instead of resurfacing in the failover loop."""
        try:
            status = getattr(resp, 'status', None)
            if status is None:
                status = resp.code
            state['client_started'] = True
            handler.send_response(status)
            seen = set()
            for k, v in resp.headers.items():
                if k.lower() in _HOP_HEADERS:
                    continue
                handler.send_header(k, v)
                seen.add(k.lower())
            if 'x-request-id' not in seen:
                handler.send_header('X-Request-Id', handler.request_id)
            # Deliberately one-sided: X-Served-By exists for humans
            # reading curl output / access logs, no code reads it.
            handler.send_header('X-Served-By', view.url)  # skylint: disable=header-discipline
            length = resp.headers.get('Content-Length')
            if length is not None:
                handler.send_header('Content-Length', length)
                handler.end_headers()
            else:
                handler.send_header('Transfer-Encoding', 'chunked')
                handler.end_headers()
            while True:
                chunk = resp.read1(64 * 1024)
                if length is not None:
                    if not chunk:
                        break
                    handler.wfile.write(chunk)
                else:
                    if not chunk:
                        handler.wfile.write(b'0\r\n\r\n')
                        break
                    handler.wfile.write(f'{len(chunk):x}\r\n'.encode())
                    handler.wfile.write(chunk)
                    handler.wfile.write(b'\r\n')
                handler.wfile.flush()
        except (OSError, ConnectionError, TimeoutError,
                http.client.HTTPException) as e:
            logger.warning(f'mid-relay failure via {view.url}: {e!r}; '
                           'closing client connection')
            handler.close_connection = True
