"""Self-hosted serve controller: the service runtime survives the
client.

Reference semantics (sky/serve/core.py:136 + sky-serve-controller.yaml
.j2): `sky serve up` launches the *controller cluster* first, then the
per-service controller + load balancer run THERE — so autoscaling,
readiness probing, and replica recovery continue when the submitting
laptop disappears.  Same deployment shift as the self-hosted jobs
controller (jobs/remote.py), riding identical machinery:

  - a small reusable controller cluster (default
    `skytpu-serve-controller`, resources from config
    serve.controller.resources) provisioned through the normal
    optimizer/provisioner path — the framework launching itself;
  - the service task YAML is file-mounted and the agent job runs
    `python -m skypilot_tpu.serve.remote --task <yaml> --service-name
    <n>` ON the controller host, which registers the service in the
    HOST's serve state and starts the detached service runtime there
    (serve/core.py mode='process');
  - client-side queries (`--remote-controller` CLI flags) are module
    invocations on the controller head, JSON between sentinel markers
    (the reference's codegen-RPC idea without base64 payload blobs).
"""
from __future__ import annotations

import argparse
import json
import os
import shlex
import shutil
import tempfile
import time
import uuid
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib

logger = sky_logging.init_logger(__name__)

_TASK_MOUNT_DIR = 'skytpu_services'
_RESPONSE_BEGIN = '<skytpu-serve-remote>'
_RESPONSE_END = '</skytpu-serve-remote>'


def controller_cluster_name() -> str:
    from skypilot_tpu import config
    return config.get_nested(('serve', 'controller', 'cluster_name'),
                             'skytpu-serve-controller')


def controller_resources() -> Any:
    """Default controller shape (reference
    controller_utils.get_controller_resources)."""
    from skypilot_tpu import config
    from skypilot_tpu import resources as resources_lib
    spec = config.get_nested(('serve', 'controller', 'resources'), None)
    if spec:
        return resources_lib.Resources.from_yaml_config(spec)
    return resources_lib.Resources(cloud='gcp', cpus='4+')


def _run_controller_job(cluster: str, run_cmd_fmt: str,
                        local_yaml_src: str, basename: str,
                        resources: Optional[Any],
                        what: str) -> Dict[str, Any]:
    """Ship a YAML to the controller cluster, run a skypilot_tpu.serve.
    remote invocation there as a detached agent job, and poll its framed
    response (shared by up()/update())."""
    from skypilot_tpu import execution
    controller_task = task_lib.Task(
        name=what,
        run=run_cmd_fmt.format(path=f'../{_TASK_MOUNT_DIR}/{basename}'),
    )
    controller_task.set_file_mounts(
        {f'{_TASK_MOUNT_DIR}/{basename}': local_yaml_src})
    if resources is not None:
        controller_task.set_resources(resources)
    job_id, handle = execution.launch(controller_task,
                                      cluster_name=cluster,
                                      detach_run=True,
                                      quiet_optimizer=True)
    deadline = time.time() + 300
    last: Dict[str, Any] = {}
    while time.time() < deadline:
        try:
            last = _read_job_response(handle, job_id)
            break
        except exceptions.SkyTpuError:
            time.sleep(2)
    if not last:
        raise exceptions.ServeUserTerminatedError(
            f'{what} on controller cluster {cluster!r} produced no '
            f'response within 300s; see: sky logs {cluster} {job_id}')
    if 'error' in last:
        raise exceptions.ServeUserTerminatedError(last['error'])
    last['_handle'] = handle
    return last


def up(task: task_lib.Task,
       service_name: Optional[str] = None,
       controller_cluster: Optional[str] = None,
       resources: Optional[Any] = None) -> Dict[str, Any]:
    """Deploy a service whose runtime lives on the controller cluster.

    Returns {'service_name', 'endpoint', 'controller_cluster'} — the
    endpoint is the controller host address with the LB port."""
    if task.service is None:
        raise exceptions.TaskValidationError(
            'Task must define a `service` section for sky serve up.')
    if service_name is None:
        service_name = f'service-{uuid.uuid4().hex[:4]}'
    # Reject bad names before a controller cluster gets provisioned.
    from skypilot_tpu.serve import serve_utils
    serve_utils.validate_service_name(service_name)
    cluster = controller_cluster or controller_cluster_name()

    # Mount path is name-free (names are validated, but keep shell
    # quoting concerns out of the path entirely).
    basename = f'svc-{uuid.uuid4().hex[:8]}.yaml'
    local_dir = tempfile.mkdtemp(prefix='skytpu-serve-')
    local_yaml = os.path.join(local_dir, basename)
    from skypilot_tpu.utils import common_utils
    common_utils.dump_yaml(local_yaml, task.to_yaml_config())
    run_fmt = ('python3 -m skypilot_tpu.serve.remote --task {path} '
               f'--service-name {shlex.quote(service_name)}')
    try:
        last = _run_controller_job(
            cluster, run_fmt, local_yaml, basename,
            resources or controller_resources(),
            f'serve-{service_name}')
    finally:
        shutil.rmtree(local_dir, ignore_errors=True)
    endpoint = _rewrite_endpoint(last.get('endpoint', ''),
                                 last['_handle'])
    logger.info(
        f'Service {service_name!r} deployed on controller cluster '
        f'{cluster!r} at {endpoint}; the runtime survives this client.')
    return {'service_name': service_name, 'endpoint': endpoint,
            'controller_cluster': cluster}


def update(task: task_lib.Task, service_name: str,
           controller_cluster: Optional[str] = None) -> int:
    """Rolling-update a service on the controller cluster: ship the new
    task YAML there and bump the service version (reference
    serve/core.py:362 semantics, controller-hosted)."""
    if task.service is None:
        raise exceptions.TaskValidationError(
            'Task must define a `service` section.')
    cluster = controller_cluster or controller_cluster_name()
    # Update targets an EXISTING controller; never provision one as a
    # side effect (a missing controller means there is no service).
    from skypilot_tpu import global_user_state
    if global_user_state.get_cluster_from_name(cluster) is None:
        raise exceptions.ClusterDoesNotExist(
            f'Serve controller cluster {cluster!r} does not exist; '
            'deploy with `sky serve up --remote-controller` first.')
    basename = f'svc-update-{uuid.uuid4().hex[:8]}.yaml'
    local_dir = tempfile.mkdtemp(prefix='skytpu-serve-')
    local_yaml = os.path.join(local_dir, basename)
    from skypilot_tpu.utils import common_utils
    common_utils.dump_yaml(local_yaml, task.to_yaml_config())
    run_fmt = ('python3 -m skypilot_tpu.serve.remote '
               '--update-task {path} '
               f'--service-name {shlex.quote(service_name)}')
    try:
        last = _run_controller_job(cluster, run_fmt, local_yaml,
                                   basename, None,
                                   f'serve-update-{service_name}')
    finally:
        shutil.rmtree(local_dir, ignore_errors=True)
    version = int(last['version'])
    logger.info(f'Service {service_name!r} updating to version '
                f'{version} on controller {cluster!r}.')
    return version


def _rewrite_endpoint(endpoint: str, handle) -> str:
    """The controller host reports its local endpoint; expose it via
    the address the CLIENT can reach (the same one SSH uses), not the
    VPC-internal IP."""
    if not endpoint:
        return endpoint
    port = endpoint.rsplit(':', 1)[-1]
    address = handle.head_address
    if address.startswith('local:'):
        address = '127.0.0.1'
    elif address.startswith(('k8s:', 'docker:')):
        # Exec-style substrates have no routable address; internal IP
        # is the best available hint.
        address = handle.head_internal_ip
    return f'http://{address}:{port}'


def _read_job_response(handle, job_id: int) -> Dict[str, Any]:
    from skypilot_tpu.utils import controller_rpc
    return controller_rpc.read_job_response(handle, job_id,
                                            _RESPONSE_BEGIN,
                                            _RESPONSE_END)


# ---------------------------------------------------------------------------
# Client-side queries (module invocation on the controller head)
# ---------------------------------------------------------------------------
def _run_remote(controller_cluster: Optional[str],
                args: str) -> Dict[str, Any]:
    from skypilot_tpu.utils import controller_rpc
    cluster = controller_cluster or controller_cluster_name()
    return controller_rpc.call(cluster, 'skypilot_tpu.serve.remote',
                               args, _RESPONSE_BEGIN, _RESPONSE_END)


def status(service_names: Optional[List[str]] = None,
           controller_cluster: Optional[str] = None
           ) -> List[Dict[str, Any]]:
    args = '--status-json'
    if service_names:
        args += ' --service-names ' + ' '.join(
            shlex.quote(s) for s in service_names)
    services = _run_remote(controller_cluster, args)['services']
    # Endpoints are controller-local (http://127.0.0.1:port); translate
    # to the client-reachable controller address, as up() does.
    from skypilot_tpu import global_user_state
    cluster = controller_cluster or controller_cluster_name()
    record = global_user_state.get_cluster_from_name(cluster)
    if record is not None:
        for s in services:
            if s.get('endpoint'):
                s['endpoint'] = _rewrite_endpoint(s['endpoint'],
                                                  record['handle'])
    else:
        logger.warning(
            f'Controller cluster {cluster!r} record vanished mid-query; '
            'endpoints shown are controller-local.')
    return services


def down(service_names: Optional[List[str]] = None, *,
         all_services: bool = False, purge: bool = False,
         controller_cluster: Optional[str] = None) -> List[str]:
    if all_services:
        args = '--down-all'
    elif service_names:
        args = '--down ' + ' '.join(shlex.quote(s)
                                    for s in service_names)
    else:
        return []
    if purge:
        args += ' --purge'
    return _run_remote(controller_cluster, args)['down']


# ---------------------------------------------------------------------------
# Controller-host side
# ---------------------------------------------------------------------------
def _emit(payload: Dict[str, Any]) -> None:
    from skypilot_tpu.utils import controller_rpc
    controller_rpc.emit(payload, _RESPONSE_BEGIN, _RESPONSE_END)


def _register_service(task_path: str, service_name: str) -> None:
    from skypilot_tpu.serve import core as serve_core
    try:
        task = task_lib.Task.from_yaml(os.path.expanduser(task_path))
        name, endpoint = serve_core.up(task, service_name,
                                       mode='process')
        _emit({'service_name': name, 'endpoint': endpoint})
    except Exception as e:  # noqa: BLE001 — reported to the client
        _emit({'error': f'{type(e).__name__}: {e}'})
        raise


def _update_service(task_path: str, service_name: str) -> None:
    from skypilot_tpu.serve import core as serve_core
    try:
        task = task_lib.Task.from_yaml(os.path.expanduser(task_path))
        version = serve_core.update(task, service_name)
        _emit({'service_name': service_name, 'version': version})
    except Exception as e:  # noqa: BLE001 — reported to the client
        _emit({'error': f'{type(e).__name__}: {e}'})
        raise


def _status_json(service_names: Optional[List[str]]) -> None:
    from skypilot_tpu.serve import core as serve_core
    services = serve_core.status(service_names)
    for s in services:
        for key, value in list(s.items()):
            if hasattr(value, 'value'):
                s[key] = value.value
        for r in s.get('replica_info', []):
            for key, value in list(r.items()):
                if hasattr(value, 'value'):
                    r[key] = value.value
    _emit({'services': services})


def main(argv: Optional[List[str]] = None) -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--task', default=None)
    parser.add_argument('--update-task', default=None)
    parser.add_argument('--service-name', default=None)
    parser.add_argument('--status-json', action='store_true')
    parser.add_argument('--service-names', nargs='+', default=None)
    parser.add_argument('--down', nargs='+', default=None)
    parser.add_argument('--down-all', action='store_true')
    parser.add_argument('--purge', action='store_true')
    args = parser.parse_args(argv)

    if args.task:
        _register_service(args.task, args.service_name)
    elif args.update_task:
        _update_service(args.update_task, args.service_name)
    elif args.status_json:
        _status_json(args.service_names)
    elif args.down or args.down_all:
        from skypilot_tpu.serve import core as serve_core
        from skypilot_tpu.serve import serve_state
        names = (args.down if args.down else
                 [s['name'] for s in serve_state.get_services()])
        serve_core.down(args.down, all_services=args.down_all,
                        purge=args.purge)
        _emit({'down': names})
    else:
        parser.error('Nothing to do.')


if __name__ == '__main__':
    main()
