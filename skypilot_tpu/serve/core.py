"""Serve SDK: up / update / down / status (reference sky/serve/core.py).

`up` (:136) persists the service + task, then starts the service runtime
(controller + load balancer) — detached process by default, or
in-process for hermetic tests; `update` (:362) bumps the service
version for a rolling update; `down` (:525) terminates replicas and the
runtime; `status` (:635) reads the state DB.
"""
from __future__ import annotations

import os
import signal
import sys
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple, Union

from skypilot_tpu import usage
from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve import serve_utils
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import subprocess_utils

logger = sky_logging.init_logger(__name__)

# In-process runtimes (mode='inline'), keyed by service name.
_INLINE_RUNTIMES: Dict[str, Any] = {}


def _extract_task(entrypoint: Union[task_lib.Task, 'dag_lib.Dag']
                  ) -> task_lib.Task:
    if isinstance(entrypoint, dag_lib.Dag):
        if len(entrypoint.tasks) != 1:
            raise exceptions.NotSupportedError(
                'Services must be single-task.')
        return entrypoint.tasks[0]
    return entrypoint


@usage.entrypoint('sky.serve.up')
def up(task: Union[task_lib.Task, 'dag_lib.Dag'],
       service_name: Optional[str] = None,
       mode: str = 'process',
       **runtime_kwargs: Any) -> Tuple[str, str]:
    """Spin up a service; returns (service_name, endpoint).

    mode: 'process' (default; detached service runtime) or 'inline'
    (runtime threads in this process — hermetic tests; extra
    runtime_kwargs like autoscaler_interval_seconds are honored).
    """
    task = _extract_task(task)
    if task.service is None:
        raise exceptions.TaskValidationError(
            'Task must define a `service` section for sky serve up.')
    if service_name is None:
        service_name = f'service-{uuid.uuid4().hex[:4]}'
    serve_utils.validate_service_name(service_name)
    task.validate()

    spec = task.service
    service_dir = serve_state.service_dir(service_name)
    task_yaml_path = os.path.join(service_dir, 'task_v1.yaml')
    common_utils.dump_yaml(task_yaml_path, task.to_yaml_config())
    resources_str = ', '.join(
        str(r) for r in task.get_preferred_resources())
    # Lock port allocation + registration together: two concurrent `up`
    # calls must not be handed the same controller/LB ports.
    import filelock
    from skypilot_tpu.utils import paths
    lock = filelock.FileLock(
        os.path.join(paths.locks_dir(), 'serve_ports.lock'))
    with lock:
        ports = serve_utils.allocate_ports()
        ok = serve_state.add_service(
            service_name,
            spec_yaml=common_utils.dump_yaml_str(spec.to_yaml_config()),
            task_yaml_path=task_yaml_path,
            controller_port=ports['controller_port'],
            load_balancer_port=ports['load_balancer_port'],
            policy=spec.load_balancing_policy,
            requested_resources_str=resources_str)
    if not ok:
        raise exceptions.ServeUserTerminatedError(
            f'Service {service_name!r} already exists. Use '
            '`sky serve update` to update it or `down` to remove it.')

    if mode == 'process':
        log_path = os.path.join(service_dir, 'service.log')
        pid = subprocess_utils.launch_new_process_tree(
            f'{sys.executable} -m skypilot_tpu.serve.service '
            f'--service-name {service_name}', log_output=log_path)
        serve_state.set_service_controller_pid(service_name, pid)
    elif mode == 'inline':
        from skypilot_tpu.serve import service as service_lib
        runtime = service_lib.ServiceRuntime(service_name, **runtime_kwargs)
        runtime.start()
        _INLINE_RUNTIMES[service_name] = runtime
    else:
        raise ValueError(f'Unknown mode {mode!r}')

    record = serve_state.get_service(service_name)
    endpoint = serve_utils.get_endpoint(record)
    logger.info(f'Service {service_name!r} spinning up at {endpoint} '
                f'({mode} runtime).')
    return service_name, endpoint


def update(task: Union[task_lib.Task, 'dag_lib.Dag'],
           service_name: str) -> int:
    """Rolling update: persist the new spec/task as version N+1 and tell
    the controller (reference serve/core.py:362)."""
    task = _extract_task(task)
    if task.service is None:
        raise exceptions.TaskValidationError(
            'Task must define a `service` section.')
    record = serve_state.get_service(service_name)
    if record is None:
        raise exceptions.ServeUserTerminatedError(
            f'Service {service_name!r} does not exist.')
    task.validate()
    new_version = record['version'] + 1
    task_yaml_path = os.path.join(serve_state.service_dir(service_name),
                                  f'task_v{new_version}.yaml')
    common_utils.dump_yaml(task_yaml_path, task.to_yaml_config())
    serve_state.set_service_version(
        service_name, new_version,
        spec_yaml=common_utils.dump_yaml_str(
            task.service.to_yaml_config()),
        task_yaml_path=task_yaml_path)
    # Notify the runtime.
    if service_name in _INLINE_RUNTIMES:
        _INLINE_RUNTIMES[service_name].controller.update_service_version(
            new_version)
    else:
        import json
        import urllib.request
        req = urllib.request.Request(
            f'http://127.0.0.1:{record["controller_port"]}'
            '/controller/update_service',
            data=json.dumps({'version': new_version}).encode(),
            headers={'Content-Type': 'application/json'})
        with urllib.request.urlopen(req, timeout=10):
            pass
    logger.info(f'Service {service_name!r} updated to version '
                f'{new_version}.')
    return new_version


@usage.entrypoint('sky.serve.down')
def down(service_names: Optional[Union[str, List[str]]] = None,
         all_services: bool = False, purge: bool = False) -> None:
    """Terminate services: replicas first, then the runtime
    (reference serve/core.py:525)."""
    if all_services:
        names = [s['name'] for s in serve_state.get_services()]
    elif service_names is None:
        raise ValueError('Provide service names or all_services=True.')
    elif isinstance(service_names, str):
        names = [service_names]
    else:
        names = list(service_names)
    for name in names:
        record = serve_state.get_service(name)
        if record is None:
            if purge:
                continue
            raise exceptions.ServeUserTerminatedError(
                f'Service {name!r} does not exist.')
        if name in _INLINE_RUNTIMES:
            runtime = _INLINE_RUNTIMES.pop(name)
            runtime.stop(terminate_replicas=True)
        elif record['controller_pid'] and _is_service_runtime(
                record['controller_pid'], name):
            try:
                # The runtime's SIGTERM handler tears replicas down.
                os.kill(record['controller_pid'], signal.SIGTERM)
                deadline = time.time() + 60
                while (time.time() < deadline and
                       subprocess_utils.process_alive(
                           record['controller_pid'])):
                    time.sleep(0.2)
            except ProcessLookupError:
                pass
            _cleanup_orphan_replicas(name)
            serve_state.remove_service(name)
        else:
            _cleanup_orphan_replicas(name)
            serve_state.remove_service(name)
        logger.info(f'Service {name!r} terminated.')


def _is_service_runtime(pid: int, service_name: str) -> bool:
    """Guard against PID reuse: only signal a process that really is
    this service's runtime."""
    try:
        with open(f'/proc/{pid}/cmdline', 'rb') as f:
            cmdline = f.read().decode(errors='replace').replace('\0', ' ')
        return ('skypilot_tpu.serve.service' in cmdline and
                service_name in cmdline)
    except OSError:
        return False


def _cleanup_orphan_replicas(service_name: str) -> None:
    """Best-effort teardown of replica clusters whose runtime is gone."""
    from skypilot_tpu import core as sky_core
    for r in serve_state.get_replicas(service_name):
        if not r['cluster_name']:
            continue
        try:
            sky_core.down(r['cluster_name'])
        except exceptions.ClusterDoesNotExist:
            pass
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(
                f'Failed to tear down replica cluster '
                f'{r["cluster_name"]}: {e}')


def status(service_names: Optional[Union[str, List[str]]] = None
           ) -> List[Dict[str, Any]]:
    """Service records with their replica lists
    (reference serve/core.py:635)."""
    records = serve_state.get_services()
    if service_names is not None:
        if isinstance(service_names, str):
            service_names = [service_names]
        records = [r for r in records if r['name'] in service_names]
    for rec in records:
        rec['replica_info'] = serve_state.get_replicas(rec['name'])
        rec['endpoint'] = serve_utils.get_endpoint(rec)
    return records


def tail_logs(service_name: str) -> str:
    """The service runtime's log (controller + LB + autoscaler)."""
    path = os.path.join(serve_state.service_dir(service_name),
                        'service.log')
    if os.path.exists(path):
        with open(path, encoding='utf-8') as f:
            return f.read()
    return ''
