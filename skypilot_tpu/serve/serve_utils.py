"""Serve helpers: port allocation, name validation, status formatting.

Counterpart of the reference's sky/serve/serve_utils.py (1,044 LoC,
mostly codegen-RPC which this rebuild replaces with direct HTTP to the
controller — see controller.py).
"""
from __future__ import annotations

import re
import socket
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.serve import constants
from skypilot_tpu.serve import serve_state

_SERVICE_NAME_RE = re.compile(r'^[a-z]([a-z0-9-]{0,48}[a-z0-9])?$')


def validate_service_name(name: str) -> None:
    if not _SERVICE_NAME_RE.match(name):
        raise exceptions.TaskValidationError(
            f'Service name {name!r} is invalid: must match '
            f'{_SERVICE_NAME_RE.pattern} (lowercase, digits, dashes).')


def _port_is_free(port: int) -> bool:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        try:
            s.bind(('127.0.0.1', port))
            return True
        except OSError:
            return False


def allocate_ports() -> Dict[str, int]:
    """Next free (controller, load balancer) port pair."""
    used_ctrl = serve_state.max_used_port('controller_port')
    used_lb = serve_state.max_used_port('load_balancer_port')
    ctrl = max(constants.CONTROLLER_PORT_START, (used_ctrl or 0) + 1)
    lb = max(constants.LOAD_BALANCER_PORT_START, (used_lb or 0) + 1)
    while not _port_is_free(ctrl):
        ctrl += 1
    while not _port_is_free(lb):
        lb += 1
    return {'controller_port': ctrl, 'load_balancer_port': lb}


def format_service_table(records: List[Dict[str, Any]]) -> str:
    if not records:
        return 'No existing services.'
    headers = ['NAME', 'VERSION', 'STATUS', 'REPLICAS', 'ENDPOINT']
    rows = []
    for rec in records:
        replicas = serve_state.get_replicas(rec['name'])
        n_ready = sum(1 for r in replicas if r['status'] ==
                      serve_state.ReplicaStatus.READY)
        rows.append([
            rec['name'],
            str(rec['version']),
            rec['status'].value,
            f'{n_ready}/{len(replicas)}',
            f'http://127.0.0.1:{rec["load_balancer_port"]}',
        ])
    widths = [max(len(h), *(len(r[i]) for r in rows))
              for i, h in enumerate(headers)]
    lines = ['  '.join(h.ljust(w) for h, w in zip(headers, widths))]
    for row in rows:
        lines.append('  '.join(c.ljust(w) for c, w in zip(row, widths)))
    return '\n'.join(lines)


def format_replica_table(service_name: str) -> str:
    replicas = serve_state.get_replicas(service_name)
    if not replicas:
        return 'No replicas.'
    headers = ['ID', 'VERSION', 'STATUS', 'SPOT', 'ENDPOINT', 'CLUSTER']
    rows = [[str(r['replica_id']), str(r['version']), r['status'].value,
             'spot' if r['is_spot'] else 'on-demand',
             r['endpoint'] or '-', r['cluster_name'] or '-']
            for r in replicas]
    widths = [max(len(h), *(len(r[i]) for r in rows))
              for i, h in enumerate(headers)]
    lines = ['  '.join(h.ljust(w) for h, w in zip(headers, widths))]
    for row in rows:
        lines.append('  '.join(c.ljust(w) for c, w in zip(row, widths)))
    return '\n'.join(lines)


def get_endpoint(record: Dict[str, Any]) -> str:
    return f'http://127.0.0.1:{record["load_balancer_port"]}'
