"""SkyServe controller: autoscaler loop + LB sync endpoint.

Counterpart of the reference's sky/serve/controller.py:36
`SkyServeController` — a small HTTP app exposing
`/controller/load_balancer_sync` (the LB posts request timestamps, gets
back the ready-replica set) and `/controller/update_service`, plus a
periodic `_run_autoscaler` loop (:64) that feeds request stats into the
autoscaler and applies its decisions through the replica manager.

Built on stdlib http.server (threaded) instead of FastAPI/uvicorn: the
control plane has no dependency beyond the framework itself.
"""
from __future__ import annotations

import http.server
import json
import threading
import time
import typing
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.serve import autoscalers
from skypilot_tpu.serve import constants
from skypilot_tpu.serve import replica_managers
from skypilot_tpu.serve import serve_state

if typing.TYPE_CHECKING:
    from skypilot_tpu.serve import service_spec as spec_lib

logger = sky_logging.init_logger(__name__)

# Method surfaces, for the wrong-method 405+Allow guards below.
_GET_ROUTES = ('/controller/health', '/services', '/api/services')
_POST_ROUTES = ('/controller/load_balancer_sync',
                '/controller/update_service')


class SkyServeController:

    def __init__(self, service_name: str, spec: 'spec_lib.SkyServiceSpec',
                 task_yaml_path: str, port: int,
                 autoscaler_interval_seconds: float =
                 constants.AUTOSCALER_INTERVAL_SECONDS,
                 probe_interval_seconds: float =
                 constants.PROBE_INTERVAL_SECONDS) -> None:
        self.service_name = service_name
        self.port = port
        self.autoscaler_interval = autoscaler_interval_seconds
        self.probe_interval = probe_interval_seconds
        self.replica_manager = replica_managers.ReplicaManager(
            service_name, spec, task_yaml_path)
        self.autoscaler = autoscalers.Autoscaler.from_spec(spec)
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._server: Optional[http.server.ThreadingHTTPServer] = None

    # -- loops -------------------------------------------------------------
    def _autoscaler_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._run_autoscaler_once()
            except Exception as e:  # pylint: disable=broad-except
                logger.warning(f'Autoscaler iteration failed: {e}')
            self._stop.wait(self.autoscaler_interval)

    def _run_autoscaler_once(self) -> None:
        # Scaling decisions consider only current-version replicas;
        # old-version replicas keep serving (surge) and are removed by
        # the drain path below once the new version has READY capacity
        # (reference replica_managers.py:1172 rolling update).
        version = self.replica_manager.version
        replicas = [r for r in serve_state.get_replicas(self.service_name)
                    if r['version'] == version]
        decision = self.autoscaler.evaluate_scaling(replicas)
        for up in decision.scale_up:
            for _ in range(up.count):
                rid = self.replica_manager.launch_replica(
                    use_spot=up.use_spot)
                logger.info(f'Scaling up {self.service_name}: replica '
                            f'{rid} (spot={up.use_spot}).')
        for down in decision.scale_down:
            for rid in down.replica_ids:
                logger.info(f'Scaling down {self.service_name}: replica '
                            f'{rid}.')
                self.replica_manager.scale_down_replica(rid)
        # Rolling update: drain old-version replicas once the new
        # version has enough READY capacity.
        for rid in self.replica_manager.old_version_replicas_to_drain():
            logger.info(f'Rolling update: draining old replica {rid}.')
            self.replica_manager.scale_down_replica(rid)
        # PREEMPTED rows are informational while the replacement is in
        # flight; purge them once READY capacity is restored so the
        # replica table doesn't grow without bound on spotty services.
        n_ready = sum(1 for r in replicas
                      if r['status'] == serve_state.ReplicaStatus.READY)
        if n_ready >= self.autoscaler.spec.min_replicas:
            for r in serve_state.get_replicas(self.service_name):
                if r['status'] == serve_state.ReplicaStatus.PREEMPTED:
                    serve_state.remove_replica(self.service_name,
                                               r['replica_id'])
        self._refresh_service_status()

    def _prober_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.replica_manager.probe_all()
                self._refresh_service_status()
            except Exception as e:  # pylint: disable=broad-except
                logger.warning(f'Prober iteration failed: {e}')
            self._stop.wait(self.probe_interval)

    def _refresh_service_status(self) -> None:
        record = serve_state.get_service(self.service_name)
        if record is None or record['status'] in (
                serve_state.ServiceStatus.SHUTTING_DOWN,):
            return
        replicas = serve_state.get_replicas(self.service_name)
        n_ready = sum(1 for r in replicas
                      if r['status'] == serve_state.ReplicaStatus.READY)
        alive = [r for r in replicas if not r['status'].is_terminal()]
        if n_ready > 0:
            status = serve_state.ServiceStatus.READY
        elif alive:
            status = serve_state.ServiceStatus.REPLICA_INIT
        elif replicas and all(r['status'].is_terminal() for r in replicas):
            status = serve_state.ServiceStatus.FAILED
        else:
            status = serve_state.ServiceStatus.NO_REPLICA
        if status != record['status']:
            serve_state.set_service_status(self.service_name, status)

    # -- HTTP (LB sync + service ops) --------------------------------------
    def _make_handler(self):
        controller = self

        class Handler(http.server.BaseHTTPRequestHandler):

            def log_message(self, *args: Any) -> None:
                pass

            def _send_json(self, obj: Any,
                           code: int = 200) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header('Content-Type', 'application/json')
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_405(self, allow: str) -> None:
                # Explicit wrong-method answer: the stdlib default is
                # a bare 501, which callers read as a controller bug.
                self.send_response(405)
                self.send_header('Allow', allow)
                self.send_header('Content-Length', '0')
                self.end_headers()

            def do_POST(self) -> None:  # noqa: N802
                if self.path.split('?', 1)[0].rstrip('/') \
                        in _GET_ROUTES:
                    self._send_405('GET')
                    return
                length = int(self.headers.get('Content-Length', 0))
                payload = json.loads(self.rfile.read(length) or b'{}')
                if self.path == '/controller/load_balancer_sync':
                    timestamps = payload.get('request_aggregator',
                                             {}).get('timestamps', [])
                    controller.autoscaler.collect_request_information(
                        timestamps)
                    self._send_json({
                        'ready_replica_urls':
                            controller.replica_manager
                            .ready_replica_endpoints()})
                elif self.path == '/controller/update_service':
                    version = payload['version']
                    controller.update_service_version(version)
                    self._send_json({'version': version})
                else:
                    self._send_json({'error': 'not found'}, code=404)

            def do_GET(self) -> None:  # noqa: N802
                from skypilot_tpu.serve import dashboard
                path = self.path.split('?', 1)[0].rstrip('/')
                if path in _POST_ROUTES:
                    self._send_405('POST')
                elif path == '/controller/health':
                    self._send_json({'service': controller.service_name})
                elif path == '/services':
                    # Browsable `sky serve status` analog, scoped to
                    # this controller's service.
                    body = dashboard.render_index(
                        controller.service_name).encode()
                    self.send_response(200)
                    self.send_header('Content-Type', 'text/html')
                    self.send_header('Content-Length', str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif path == '/api/services':
                    # Bare list — same shape as the standalone
                    # dashboard's API, so the HTML page's fetch works
                    # against either server.
                    self._send_json(dashboard.services_snapshot(
                        controller.service_name))
                else:
                    self._send_json({'error': 'not found'}, code=404)

        return Handler

    def update_service_version(self, version: int) -> None:
        """Adopt the (already persisted) spec for `version`."""
        from skypilot_tpu.serve import service_spec as spec_lib
        import yaml
        record = serve_state.get_service(self.service_name)
        assert record is not None
        spec = spec_lib.SkyServiceSpec.from_yaml_config(
            yaml.safe_load(record['spec_yaml']))
        self.replica_manager.update_version(version, spec,
                                            record['task_yaml_path'])
        self.autoscaler.update_spec(spec)
        logger.info(f'Service {self.service_name} updated to version '
                    f'{version}.')

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._server = http.server.ThreadingHTTPServer(
            ('127.0.0.1', self.port), self._make_handler())
        self._server.daemon_threads = True
        for target, name in ((self._server.serve_forever, 'http'),
                             (self._autoscaler_loop, 'autoscaler'),
                             (self._prober_loop, 'prober')):
            t = threading.Thread(target=target, daemon=True,
                                 name=f'{self.service_name}-ctrl-{name}')
            t.start()
            self._threads.append(t)
        logger.info(f'Controller for {self.service_name} on port '
                    f'{self.port}.')

    def stop(self, terminate_replicas: bool = True) -> None:
        self._stop.set()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
        if terminate_replicas:
            serve_state.set_service_status(
                self.service_name, serve_state.ServiceStatus.SHUTTING_DOWN)
            self.replica_manager.terminate_all()

    def run_until_stopped(self) -> None:
        while not self._stop.is_set():
            time.sleep(0.5)
