"""Replica manager: each replica is a cluster of this framework.

Counterpart of the reference's sky/serve/replica_managers.py:608
`SkyPilotReplicaManager`: `_launch_replica` (:643) launches each replica
via `sky.launch`, background threads probe readiness
(`_replica_prober` :1026/:1130), detect preemption
(`_handle_preemption` :782), and drive rolling version updates (:1172).

Differences from the reference, deliberate:
- Launches run on daemon threads (not subprocesses) — the controller is
  already its own process; threads keep the fake/local cloud path
  hermetic.
- Probing uses stdlib urllib (no httpx dependency).
"""
from __future__ import annotations

import threading
import time
import typing
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib
from skypilot_tpu.serve import constants
from skypilot_tpu.serve import serve_state

if typing.TYPE_CHECKING:
    from skypilot_tpu.serve import service_spec as spec_lib

logger = sky_logging.init_logger(__name__)

ReplicaStatus = serve_state.ReplicaStatus

# How long a k8s replica waits for its LoadBalancer/NodePort service
# to get an external address before giving up the launch.
_K8S_ENDPOINT_TIMEOUT_S = 120.0


def _port_covered(port_specs: Optional[List[str]], port: int) -> bool:
    """True if `port` falls inside any '80' / '8000-8010' spec."""
    from skypilot_tpu.provision import common as provision_common
    return port in provision_common.expand_ports(port_specs or [])


def _resolve_replica_endpoint(handle, port: int) -> str:
    """Reachable http endpoint for a freshly launched replica.

    Local-cloud "addresses" are local:<agent-root> paths (loopback);
    k8s addresses are k8s:<ctx>/<ns>/<pod> schemes that resolve
    through the cluster's ports service (LB ingress IP / NodePort) —
    polled briefly, because LB controllers assign addresses
    asynchronously."""
    addr = handle.head_address
    if addr.startswith('local:'):
        return f'http://127.0.0.1:{port}'
    if addr.startswith('k8s:'):
        pc = getattr(handle, 'provider_config', None) or {}
        if (pc.get('port_mode') or 'loadbalancer').lower() == 'podip':
            # No external exposure on this cluster: tunnel through the
            # API server instead (kubectl port-forward to the head
            # pod) — the controller probes/routes via localhost.
            from skypilot_tpu.provision.kubernetes import port_forward
            context, namespace, pod = addr[len('k8s:'):].split('/', 2)
            pf = port_forward.get_or_create(
                pod, port, namespace=namespace,
                context=context or None)
            return f'http://127.0.0.1:{pf.local_port}'
        from skypilot_tpu.provision import api as provision_api
        deadline = time.time() + _K8S_ENDPOINT_TIMEOUT_S
        while True:
            eps = provision_api.query_ports(
                handle.provider_name, handle.cluster_name_on_cloud,
                [str(port)], provider_config=handle.provider_config)
            urls = eps.get(str(port))
            if urls:
                return f'http://{urls[0]}'
            if time.time() >= deadline:
                raise exceptions.ProvisionError(
                    f'k8s replica ports service has no external '
                    f'address for port {port} after '
                    f'{_K8S_ENDPOINT_TIMEOUT_S:.0f}s.')
            time.sleep(5)
    return f'http://{addr}:{port}'


def probe_endpoint(url: str, timeout: float,
                   post_data: Optional[Any] = None,
                   headers: Optional[Dict[str, str]] = None) -> bool:
    """One readiness probe: GET (or POST with post_data) must return 2xx
    (reference replica_managers.py:1130 _probe_replica)."""
    try:
        data = None
        req_headers = dict(headers or {})
        if post_data is not None:
            import json as json_lib
            data = json_lib.dumps(post_data).encode() \
                if not isinstance(post_data, (bytes, str)) \
                else (post_data.encode() if isinstance(post_data, str)
                      else post_data)
            req_headers.setdefault('Content-Type', 'application/json')
        req = urllib.request.Request(url, data=data, headers=req_headers)
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return 200 <= resp.status < 300
    except (urllib.error.URLError, ConnectionError, TimeoutError,
            OSError, ValueError):
        return False


class ReplicaManager:
    """Owns the replica fleet of one service."""

    def __init__(self, service_name: str, spec: 'spec_lib.SkyServiceSpec',
                 task_yaml_path: str, version: int =
                 serve_state.INITIAL_VERSION) -> None:
        self.service_name = service_name
        self.spec = spec
        self.task_yaml_path = task_yaml_path
        self.version = version
        self._launch_threads: Dict[int, threading.Thread] = {}
        self._down_threads: Dict[int, threading.Thread] = {}
        self._lock = threading.RLock()

    # -- naming ------------------------------------------------------------
    def replica_cluster_name(self, replica_id: int) -> str:
        return f'{self.service_name}-{replica_id}'

    # -- spec / version (rolling update) -----------------------------------
    def update_version(self, version: int, spec: 'spec_lib.SkyServiceSpec',
                       task_yaml_path: str) -> None:
        """Adopt a new service version; existing replicas keep their old
        version and are drained by `rolling_update_decisions`."""
        with self._lock:
            self.version = version
            self.spec = spec
            self.task_yaml_path = task_yaml_path

    def old_version_replicas_to_drain(self) -> List[int]:
        """Old-version replicas that can be scaled down because enough
        current-version replicas are READY (reference
        replica_managers.py:1172 rolling update)."""
        replicas = serve_state.get_replicas(self.service_name)
        new_ready = sum(1 for r in replicas
                        if r['version'] == self.version and
                        r['status'] == ReplicaStatus.READY)
        old = [r for r in replicas if r['version'] < self.version and
               r['status'] not in (ReplicaStatus.SHUTTING_DOWN,)]
        if new_ready >= self.spec.min_replicas:
            return [r['replica_id'] for r in old]
        return []

    # -- launch ------------------------------------------------------------
    def _build_replica_task(self, replica_id: int, port: int,
                            use_spot: bool) -> task_lib.Task:
        task = task_lib.Task.from_yaml(self.task_yaml_path)
        envs = {
            constants.REPLICA_PORT_ENV: str(port),
            constants.REPLICA_ID_ENV: str(replica_id),
            constants.SERVICE_NAME_ENV: self.service_name,
        }
        task.update_envs(envs)
        new_resources = []
        for r in task.get_preferred_resources():
            override: Dict[str, Any] = {}
            if use_spot:
                override['use_spot'] = True
            # The replica's serving port must be OPENED, not just
            # listened on: clouds with managed firewalls (and the k8s
            # LB/NodePort service) only expose ports declared on the
            # resources.
            if not _port_covered(r.ports, port):
                override['ports'] = list(r.ports or []) + [str(port)]
            new_resources.append(r.copy(**override) if override else r)
        task.set_resources(new_resources)
        return task

    def _replica_port(self, replica_id: int, cloud: Optional[str]) -> int:
        """Local-cloud replicas share the host network: give each its own
        port.  Real clouds: every replica has its own address; use the
        spec's port."""
        if cloud == 'local':
            return constants.LOCAL_REPLICA_PORT_START + replica_id
        return self.spec.port

    def launch_replica(self, use_spot: bool = False) -> int:
        """Start one replica launch (async); returns its replica id."""
        with self._lock:
            replica_id = serve_state.next_replica_id(self.service_name)
            cluster_name = self.replica_cluster_name(replica_id)
            serve_state.add_replica(self.service_name, replica_id,
                                    cluster_name, use_spot, self.version)
            thread = threading.Thread(
                target=self._launch_replica_blocking,
                args=(replica_id, cluster_name, use_spot),
                name=f'launch-{cluster_name}', daemon=True)
            self._launch_threads[replica_id] = thread
            thread.start()
            return replica_id

    def _launch_replica_blocking(self, replica_id: int, cluster_name: str,
                                 use_spot: bool) -> None:
        from skypilot_tpu import execution
        serve_state.set_replica_status(self.service_name, replica_id,
                                       ReplicaStatus.PROVISIONING)
        try:
            task = task_lib.Task.from_yaml(self.task_yaml_path)
            cloud = None
            prefs = task.get_preferred_resources()
            if prefs and prefs[0].cloud is not None:
                cloud = prefs[0].cloud.canonical_name()
            port = self._replica_port(replica_id, cloud)
            task = self._build_replica_task(replica_id, port, use_spot)
            _, handle = execution.launch(
                task, cluster_name=cluster_name, detach_run=True,
                stream_logs=False, quiet_optimizer=True)
            endpoint = _resolve_replica_endpoint(handle, port)
            serve_state.set_replica_endpoint(self.service_name, replica_id,
                                             endpoint)
            serve_state.set_replica_status(self.service_name, replica_id,
                                           ReplicaStatus.STARTING)
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(f'Replica {replica_id} of {self.service_name} '
                           f'failed to launch: {e}')
            serve_state.set_replica_status(
                self.service_name, replica_id, ReplicaStatus.FAILED,
                failure_reason=str(e))

    # -- teardown ----------------------------------------------------------
    def scale_down_replica(self, replica_id: int,
                           preempted: bool = False) -> None:
        with self._lock:
            if replica_id in self._down_threads:
                return
            serve_state.set_replica_status(self.service_name, replica_id,
                                           ReplicaStatus.SHUTTING_DOWN)
            thread = threading.Thread(
                target=self._terminate_replica_blocking,
                args=(replica_id, preempted),
                name=f'down-{self.replica_cluster_name(replica_id)}',
                daemon=True)
            self._down_threads[replica_id] = thread
            thread.start()

    def _terminate_replica_blocking(self, replica_id: int,
                                    preempted: bool) -> None:
        from skypilot_tpu import core
        cluster_name = self.replica_cluster_name(replica_id)
        try:
            try:
                core.down(cluster_name)
            except exceptions.ClusterDoesNotExist:
                pass
            if preempted:
                # Keep the row: PREEMPTED is informational until the
                # autoscaler replaces it, then it ages out below.
                serve_state.set_replica_status(
                    self.service_name, replica_id, ReplicaStatus.PREEMPTED)
            else:
                serve_state.remove_replica(self.service_name, replica_id)
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(f'Failed to clean up replica {replica_id}: {e}')
            serve_state.set_replica_status(
                self.service_name, replica_id,
                ReplicaStatus.FAILED_CLEANUP, failure_reason=str(e))
        finally:
            with self._lock:
                self._down_threads.pop(replica_id, None)

    def terminate_all(self) -> None:
        replicas = serve_state.get_replicas(self.service_name)
        for r in replicas:
            if r['status'] != ReplicaStatus.SHUTTING_DOWN:
                self.scale_down_replica(r['replica_id'])
        deadline = time.time() + 120
        while time.time() < deadline:
            with self._lock:
                threads = list(self._down_threads.values())
            if not any(t.is_alive() for t in threads):
                break
            time.sleep(0.2)

    # -- probing / preemption ---------------------------------------------
    def _cluster_status(self, cluster_name: str
                        ) -> Optional[global_user_state.ClusterStatus]:
        record = global_user_state.get_cluster_from_name(cluster_name)
        return record['status'] if record else None

    def _reresolve_tunnel_endpoint(self, record) -> Optional[str]:
        """Fresh endpoint for a podip-mode k8s replica (restarts the
        port-forward tunnel); None when the replica isn't one."""
        cluster = global_user_state.get_cluster_from_name(
            record['cluster_name'])
        if cluster is None:
            return None
        handle = cluster['handle']
        addr = getattr(handle, 'head_address', '')
        pc = getattr(handle, 'provider_config', None) or {}
        if not addr.startswith('k8s:') or \
                (pc.get('port_mode') or '').lower() != 'podip':
            return None
        try:
            # k8s replicas always serve on the spec port (per-replica
            # ports exist only on the shared-network local cloud).
            return _resolve_replica_endpoint(handle, self.spec.port)
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(
                f'could not re-resolve tunnel endpoint for replica '
                f'{record["replica_id"]}: {e}')
            return None

    def probe_all(self) -> None:
        """One prober pass (reference _replica_prober :1026): check
        cluster liveness (preemption), then HTTP readiness."""
        now = time.time()
        for r in serve_state.get_replicas(self.service_name):
            status = r['status']
            replica_id = r['replica_id']
            if status not in (ReplicaStatus.STARTING, ReplicaStatus.READY,
                              ReplicaStatus.NOT_READY):
                continue
            cluster_status = self._cluster_status(r['cluster_name'])
            if cluster_status != global_user_state.ClusterStatus.UP:
                # Reference _handle_preemption (:782): treat a vanished /
                # stopped cluster as preemption — tear down remnants (TPU
                # VMs must be deleted, not stopped) and let the
                # autoscaler replace it.
                logger.info(f'Replica {replica_id} cluster '
                            f'{r["cluster_name"]} is {cluster_status}; '
                            'handling as preemption.')
                self.scale_down_replica(replica_id, preempted=True)
                continue
            if not r['endpoint']:
                continue
            url = r['endpoint'] + self.spec.readiness_path
            ok = probe_endpoint(url, self.spec.readiness_timeout_seconds,
                                self.spec.post_data,
                                self.spec.readiness_headers)
            if not ok and r['endpoint'].startswith('http://127.0.0.1'):
                # podip-mode k8s replicas are reached through a local
                # port-forward tunnel; a failed probe may just mean
                # the tunnel died (or a controller restart lost it) —
                # re-resolve, which restarts/recreates the tunnel, and
                # re-probe before charging the replica a failure.
                fresh = self._reresolve_tunnel_endpoint(r)
                if fresh is not None:
                    if fresh != r['endpoint']:
                        serve_state.set_replica_endpoint(
                            self.service_name, replica_id, fresh)
                    url = fresh + self.spec.readiness_path
                    ok = probe_endpoint(
                        url, self.spec.readiness_timeout_seconds,
                        self.spec.post_data,
                        self.spec.readiness_headers)
            if ok:
                if status != ReplicaStatus.READY:
                    logger.info(f'Replica {replica_id} of '
                                f'{self.service_name} is READY.')
                serve_state.set_replica_status(
                    self.service_name, replica_id, ReplicaStatus.READY)
                continue
            if status == ReplicaStatus.STARTING:
                if now - (r['launched_at'] or now) > \
                        self.spec.initial_delay_seconds:
                    serve_state.set_replica_status(
                        self.service_name, replica_id, ReplicaStatus.FAILED,
                        failure_reason='Readiness probe never passed '
                        'within initial_delay_seconds.')
                    self._teardown_failed(replica_id)
                continue
            failures = serve_state.bump_replica_failures(
                self.service_name, replica_id)
            if failures >= constants.PROBE_FAILURE_THRESHOLD:
                serve_state.set_replica_status(
                    self.service_name, replica_id, ReplicaStatus.NOT_READY)

    def _teardown_failed(self, replica_id: int) -> None:
        """Tear down the cluster behind a FAILED replica but keep the row
        for `sky serve status` display."""
        from skypilot_tpu import core
        cluster_name = self.replica_cluster_name(replica_id)
        try:
            core.down(cluster_name)
        except exceptions.ClusterDoesNotExist:
            pass
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(
                f'Cleanup of failed replica {replica_id} errored: {e}')

    # -- views -------------------------------------------------------------
    def ready_replica_endpoints(self) -> List[str]:
        """All READY endpoints — including old-version replicas, which
        keep serving until the rolling update drains them."""
        return [r['endpoint']
                for r in serve_state.get_replicas(self.service_name)
                if r['status'] == ReplicaStatus.READY and r['endpoint']]
