"""The `skytpu` command-line interface.

Counterpart of the reference's click app (sky/cli.py:1073 launch, :1209
exec, :1590 status, :1982 queue, :2050 logs, :2145 cancel, :2221 stop,
:2299 autostop, :2425 start, :2622 down, :2989 check, :3042 show-gpus →
show-tpus here, :3567 jobs group, :3984 serve group).  CLI flags override
YAML fields the same way (_parse_override_params, cli.py:477).
"""
from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

import click

from skypilot_tpu import exceptions


def _sky():
    import skypilot_tpu as sky
    return sky


def _make_task(entrypoint: Tuple[str, ...], **overrides: Any):
    """YAML path or inline command → Task, with CLI overrides applied
    (reference _make_task_or_dag_from_entrypoint_with_overrides,
    cli.py:722)."""
    from skypilot_tpu import task as task_lib
    from skypilot_tpu import resources as resources_lib
    entry = ' '.join(entrypoint)
    env_overrides = overrides.pop('env', None) or []
    is_yaml = entry.endswith(('.yaml', '.yml')) and os.path.exists(
        os.path.expanduser(entry))
    if is_yaml:
        from skypilot_tpu.utils import common_utils
        config = common_utils.read_yaml(entry) or {}
        task = task_lib.Task.from_yaml_config(
            config, env_overrides=[tuple(e.split('=', 1))
                                   for e in env_overrides])
    else:
        task = task_lib.Task(run=entry or None)
        task.update_envs([tuple(e.split('=', 1)) for e in env_overrides])

    res_overrides: Dict[str, Any] = {}
    for key in ('cloud', 'region', 'zone', 'instance_type', 'cpus',
                'memory', 'accelerators', 'use_spot', 'disk_size',
                'disk_tier', 'ports', 'image_id'):
        value = overrides.pop(key, None)
        if value is not None:
            res_overrides[key] = value
    if res_overrides:
        new_resources = {
            r.copy(**res_overrides) for r in task.get_preferred_resources()
        }
        task.set_resources(new_resources)
    if overrides.get('num_nodes') is not None:
        task.num_nodes = overrides['num_nodes']
    if overrides.get('workdir') is not None:
        task.workdir = overrides['workdir']
    if overrides.get('name') is not None:
        task.name = overrides['name']
    return task


_RESOURCE_OPTIONS = [
    click.option('--cloud', default=None, help='Cloud to use.'),
    click.option('--region', default=None),
    click.option('--zone', default=None),
    click.option('--instance-type', 'instance_type', default=None),
    click.option('--cpus', default=None),
    click.option('--memory', default=None),
    click.option('--accelerators', '--gpus', '--tpus', 'accelerators',
                 default=None,
                 help="e.g. 'tpu-v5p-128' or 'tpu-v5e:16' or 'A100:8'."),
    click.option('--use-spot/--no-use-spot', 'use_spot', default=None),
    click.option('--disk-size', 'disk_size', type=int, default=None),
    click.option('--disk-tier', 'disk_tier', default=None),
    click.option('--ports', multiple=True, default=None),
    click.option('--image-id', 'image_id', default=None),
    click.option('--num-nodes', 'num_nodes', type=int, default=None),
    click.option('--workdir', default=None),
    click.option('--env', multiple=True,
                 help='Env override KEY=VALUE (repeatable).'),
]

# Task-name override is separate from _RESOURCE_OPTIONS: commands that
# already bind `-n` to something else (jobs launch, serve up) must not
# re-declare it — click warns on duplicate parameter declarations.
_TASK_NAME_OPTION = click.option('--name', '-n', default=None,
                                 help='Task name override.')


def _add_options(options):
    def wrapper(fn):
        for option in reversed(options):
            fn = option(fn)
        return fn

    return wrapper


@click.group()
@click.version_option(message='%(version)s',
                      version=__import__('skypilot_tpu').__version__)
def cli() -> None:
    """skytpu: TPU-native cloud orchestration."""


@cli.command()
@click.argument('entrypoint', nargs=-1, required=False)
@click.option('--cluster', '-c', default=None, help='Cluster name.')
@click.option('--dryrun', is_flag=True, default=False)
@click.option('--detach-run', '-d', is_flag=True, default=False)
@click.option('--idle-minutes-to-autostop', '-i', type=int, default=None)
@click.option('--down', is_flag=True, default=False,
              help='Autodown after the job (or with -i, after idle).')
@click.option('--retry-until-up', is_flag=True, default=False)
@click.option('--yes', '-y', is_flag=True, default=False)
@click.option('--docker', 'use_docker', is_flag=True, default=False,
              help='Run in a local docker container instead of a cloud '
                   'cluster (reference local_docker_backend).')
@_TASK_NAME_OPTION
@_add_options(_RESOURCE_OPTIONS)
def launch(entrypoint, cluster, dryrun, detach_run,
           idle_minutes_to_autostop, down, retry_until_up, yes,
           use_docker, **overrides) -> None:
    """Launch a task (YAML file or inline command) on a new or existing
    cluster."""
    sky = _sky()
    task = _make_task(entrypoint, **overrides)
    if not yes and not dryrun:
        click.confirm(f'Launching task on cluster {cluster or "(new)"}. '
                      'Proceed?', default=True, abort=True)
    backend = None
    if use_docker:
        from skypilot_tpu.backend import docker_backend
        backend = docker_backend.LocalDockerBackend()
    job_id, handle = sky.launch(
        task, cluster_name=cluster, dryrun=dryrun, down=down,
        detach_run=detach_run,
        idle_minutes_to_autostop=idle_minutes_to_autostop,
        retry_until_up=retry_until_up, backend=backend)
    if handle is not None:
        click.echo(f'Job {job_id} on cluster {handle.cluster_name!r}.')
    if not detach_run and job_id is not None and handle is not None:
        status_map = sky.job_status(handle.cluster_name, [job_id])
        if status_map.get(job_id) not in ('SUCCEEDED', None):
            sys.exit(int(exceptions.JobExitCode.FAILED))


@cli.command(name='exec')
@click.argument('cluster', required=True)
@click.argument('entrypoint', nargs=-1, required=True)
@click.option('--detach-run', '-d', is_flag=True, default=False)
@_TASK_NAME_OPTION
@_add_options(_RESOURCE_OPTIONS)
def exec_cmd(cluster, entrypoint, detach_run, **overrides) -> None:
    """Fast-resubmit a task to a live cluster (no provision/setup)."""
    sky = _sky()
    task = _make_task(entrypoint, **overrides)
    job_id, _ = sky.exec(task, cluster, detach_run=detach_run)
    click.echo(f'Job {job_id} submitted to {cluster!r}.')


@cli.command()
@click.argument('clusters', nargs=-1, required=False)
@click.option('--refresh', '-r', is_flag=True, default=False,
              help='Reconcile with cloud state.')
@click.option('--endpoints', 'show_endpoints', is_flag=True,
              default=False,
              help='Show reachable URLs for opened ports.')
@click.option('--endpoint', 'endpoint_port', type=int, default=None,
              help='Show the URL for ONE opened port.')
def status(clusters, refresh, show_endpoints, endpoint_port) -> None:
    """Show clusters."""
    sky = _sky()
    if show_endpoints or endpoint_port is not None:
        # Reference `sky status --endpoints CLUSTER` (core.endpoints).
        if len(clusters) != 1:
            raise click.UsageError(
                '--endpoints requires exactly one cluster name.')
        eps = sky.endpoints(clusters[0], port=endpoint_port)
        if not eps:
            click.echo('No endpoint assigned yet (LoadBalancer '
                       'pending?); retry shortly.')
            return
        for port, urls in sorted(eps.items(), key=lambda kv: int(kv[0])):
            click.echo(f'{port}: {", ".join(urls)}')
        return
    records = sky.status(list(clusters) or None, refresh=refresh)
    if not records:
        click.echo('No existing clusters.')
        return
    rows = []
    for r in records:
        handle = r['handle']
        resources_str = (f'{handle.launched_nodes}x '
                         f'{handle.launched_resources}')
        autostop = (f'{r["autostop"]}m{" (down)" if r["to_down"] else ""}'
                    if r['autostop'] >= 0 else '-')
        rows.append((r['name'], resources_str, r['status'].value, autostop))
    _print_table(('NAME', 'RESOURCES', 'STATUS', 'AUTOSTOP'), rows)


@cli.command()
@click.argument('cluster', required=True)
def queue(cluster) -> None:
    """Show a cluster's job queue."""
    jobs = _sky().queue(cluster)
    rows = [(str(j['job_id']), j['job_name'] or '-', j['status'],
             j['username']) for j in jobs]
    _print_table(('ID', 'NAME', 'STATUS', 'USER'), rows)


@cli.command()
@click.argument('cluster', required=True)
@click.argument('job_id', type=int, required=False)
@click.option('--follow/--no-follow', default=True)
@click.option('--sync-down', is_flag=True, default=False)
@click.option('--tail', type=int, default=0)
def logs(cluster, job_id, follow, sync_down, tail) -> None:
    """Tail (or download with --sync-down) a job's logs."""
    sky = _sky()
    if sync_down:
        out = sky.download_logs(cluster,
                                [job_id] if job_id is not None else None)
        for jid, path in out.items():
            click.echo(f'Job {jid} logs: {path}')
        return
    sys.exit(sky.tail_logs(cluster, job_id, follow=follow, tail=tail))


@cli.command()
@click.argument('cluster', required=True)
@click.argument('job_ids', type=int, nargs=-1)
@click.option('--all', '-a', 'all_jobs', is_flag=True, default=False)
def cancel(cluster, job_ids, all_jobs) -> None:
    """Cancel jobs."""
    cancelled = _sky().cancel(cluster, list(job_ids) or None, all_jobs)
    click.echo(f'Cancelled: {cancelled}')


@cli.command()
@click.argument('clusters', nargs=-1, required=True)
@click.option('--yes', '-y', is_flag=True, default=False)
def stop(clusters, yes) -> None:
    """Stop clusters (TPU pods cannot stop — use down)."""
    sky = _sky()
    for name in clusters:
        if not yes:
            click.confirm(f'Stop cluster {name!r}?', default=True,
                          abort=True)
        sky.stop(name)
        click.echo(f'Cluster {name!r} stopped.')


@cli.command()
@click.argument('clusters', nargs=-1, required=True)
@click.option('--retry-until-up', is_flag=True, default=False)
def start(clusters, retry_until_up) -> None:
    """Restart stopped clusters."""
    sky = _sky()
    for name in clusters:
        sky.start(name, retry_until_up=retry_until_up)
        click.echo(f'Cluster {name!r} started.')


@cli.command()
@click.argument('clusters', nargs=-1, required=True)
@click.option('--yes', '-y', is_flag=True, default=False)
@click.option('--purge', is_flag=True, default=False)
def down(clusters, yes, purge) -> None:
    """Terminate clusters."""
    sky = _sky()
    for name in clusters:
        if not yes:
            click.confirm(f'Terminate cluster {name!r}?', default=True,
                          abort=True)
        sky.down(name, purge=purge)
        click.echo(f'Cluster {name!r} terminated.')


@cli.command()
@click.argument('cluster', required=True)
@click.option('--idle-minutes', '-i', type=int, required=True,
              help='-1 cancels autostop.')
@click.option('--down', 'to_down', is_flag=True, default=False)
def autostop(cluster, idle_minutes, to_down) -> None:
    """Schedule autostop/autodown after idle."""
    _sky().autostop(cluster, idle_minutes, down=to_down)
    if idle_minutes < 0:
        click.echo(f'Autostop cancelled for {cluster!r}.')
    else:
        click.echo(f'Cluster {cluster!r} will '
                   f'{"autodown" if to_down else "autostop"} after '
                   f'{idle_minutes}m idle.')


@cli.command()
@click.argument('clouds', nargs=-1, required=False)
def check(clouds) -> None:
    """Check cloud credentials and enable usable clouds."""
    enabled = _sky().check(cloud_names=list(clouds) or None)
    click.echo(f'Enabled clouds: {", ".join(enabled) or "none"}')


def _show_accelerators(name_filter, include_gpus: bool) -> None:
    from skypilot_tpu.catalog import gcp_catalog
    inventory = gcp_catalog.list_accelerators(name_filter)
    rows = []
    gpu_rows = []
    for name in sorted(inventory):
        for item in inventory[name]:
            if 'chips' in item:
                rows.append((
                    name, str(item['chips']), str(item['hosts']),
                    f"{item['hbm_gb']:.0f}",
                    f"{item['bf16_tflops']:.0f}",
                    f"${item['price']:.2f}", f"${item['spot_price']:.2f}",
                    ','.join(item['regions'])))
            elif include_gpus:
                gpu_rows.append((
                    name, 'GCP', str(item['instance_type']),
                    f"${item['price']:.2f}",
                    f"${item['spot_price']:.2f}"))
    if include_gpus:
        from skypilot_tpu.catalog import aws_catalog
        from skypilot_tpu.catalog import azure_catalog
        from skypilot_tpu.catalog import cudo_catalog
        from skypilot_tpu.catalog import do_catalog
        from skypilot_tpu.catalog import fluidstack_catalog
        from skypilot_tpu.catalog import ibm_catalog
        from skypilot_tpu.catalog import lambda_catalog
        from skypilot_tpu.catalog import oci_catalog
        from skypilot_tpu.catalog import paperspace_catalog
        from skypilot_tpu.catalog import runpod_catalog
        from skypilot_tpu.catalog import scp_catalog
        from skypilot_tpu.catalog import vsphere_catalog
        for label, cat in (('AWS', aws_catalog),
                           ('Azure', azure_catalog),
                           ('Lambda', lambda_catalog),
                           ('RunPod', runpod_catalog),
                           ('DO', do_catalog),
                           ('Fluidstack', fluidstack_catalog),
                           ('Cudo', cudo_catalog.CATALOG),
                           ('Paperspace', paperspace_catalog.CATALOG),
                           ('IBM', ibm_catalog.CATALOG),
                           ('OCI', oci_catalog.CATALOG),
                           ('SCP', scp_catalog.CATALOG),
                           ('vSphere', vsphere_catalog.CATALOG)):
            inv = cat.list_accelerators(name_filter)
            for name in sorted(inv):
                for item in inv[name]:
                    gpu_rows.append((
                        name, label, str(item['instance_type']),
                        f"${item['price']:.2f}",
                        f"${item['spot_price']:.2f}"))
    _print_table(('TPU', 'CHIPS', 'HOSTS', 'HBM_GB', 'BF16_TFLOPS',
                  '$/HR', 'SPOT_$/HR', 'REGIONS'), rows)
    if gpu_rows:
        click.echo()
        _print_table(('GPU', 'CLOUD', 'INSTANCE_TYPE', '$/HR',
                      'SPOT_$/HR'), gpu_rows)


@cli.command(name='show-tpus')
@click.argument('name_filter', required=False)
def show_tpus(name_filter) -> None:
    """List TPU slice shapes with topology and pricing
    (reference: `sky show-gpus`)."""
    _show_accelerators(name_filter, include_gpus=False)


@cli.command(name='show-accelerators')
@click.argument('name_filter', required=False)
def show_accelerators(name_filter) -> None:
    """List ALL accelerator offerings — TPU slices and GPU VMs — with
    pricing (reference: `sky show-gpus`)."""
    _show_accelerators(name_filter, include_gpus=True)


def _catalog_for(cloud: str):
    """Catalog object (module or FlatCatalog instance — both expose
    reload/export_snapshot) for a cloud name; None only for UNKNOWN
    names — a failing import inside a known catalog module must
    surface as itself, not masquerade as 'unknown cloud'."""
    import importlib
    if cloud in ('gcp', 'aws', 'azure', 'lambda', 'runpod', 'do',
                 'fluidstack'):
        return importlib.import_module(
            f'skypilot_tpu.catalog.{cloud}_catalog')
    if cloud in ('cudo', 'paperspace', 'ibm', 'oci', 'scp',
                 'vsphere'):
        return importlib.import_module(
            f'skypilot_tpu.catalog.{cloud}_catalog').CATALOG
    return None


@cli.group()
def catalog() -> None:
    """Manage the pricing/offerings catalog cache."""


@catalog.command(name='update')
@click.option('--cloud', default='gcp')
@click.option('--table', default=None,
              help='vms | tpu_prices | tpu_zones')
@click.option('--from-file', 'from_file', default=None,
              help='Import a CSV file as the table override.')
@click.option('--url', default=None,
              help='Fetch the table from a hosted catalog URL.')
@click.option('--export', is_flag=True, default=False,
              help='Write the effective snapshot to the cache dir '
                   'as editable CSVs.')
@click.option('--reset', is_flag=True, default=False,
              help='Drop all overrides; revert to the built-in '
                   'snapshot.')
@click.option('--fetch', is_flag=True, default=False,
              help='Regenerate the tables from the cloud pricing APIs '
                   '(GCP Cloud Billing Catalog / AWS EC2 offers).')
@click.option('--api-key', default=None,
              help='API key for the GCP Billing Catalog API '
                   '(with --fetch --cloud gcp).')
@click.option('--pricing-region', default=None,
              help='Region whose prices to fetch (aws: offers region).')
def catalog_update(cloud, table, from_file, url, export, reset, fetch,
                   api_key, pricing_region) -> None:
    """Refresh the local catalog cache (reference: hosted-catalog
    fetch, sky/clouds/service_catalog/common.py + data_fetchers/)."""
    from skypilot_tpu.catalog import common as catalog_common
    if fetch:
        from skypilot_tpu.catalog import fetchers
        kwargs = {}
        if cloud == 'gcp' and api_key:
            kwargs['api_key'] = api_key
        if cloud in ('aws', 'azure') and pricing_region:
            kwargs['region'] = pricing_region
        try:
            paths = fetchers.fetch(cloud, **kwargs)
        except Exception as e:  # noqa: BLE001 — network/auth failures
            raise click.ClickException(
                f'Catalog fetch for {cloud!r} failed: {e}') from e
        for t, p in paths.items():
            click.echo(f'Fetched {t}: {p}')
        return
    cat = _catalog_for(cloud)
    if cat is None:
        raise click.UsageError(f'Unknown catalog cloud {cloud!r}.')
    tables = ('vms', 'tpu_prices', 'tpu_zones') if cloud == 'gcp' \
        else ('vms',)
    if reset:
        for t in tables:
            if catalog_common.remove_override(cloud, t):
                click.echo(f'Removed {t} override.')
        cat.reload()
        return
    if export:
        for t, text in cat.export_snapshot().items():
            click.echo(
                f'Wrote {catalog_common.write_catalog_csv(cloud, t, text)}')
        cat.reload()
        return
    if not table or not (from_file or url):
        raise click.UsageError(
            'Provide --table with --from-file or --url, or use '
            '--export / --reset.')
    if table not in tables:
        raise click.UsageError(
            f'Unknown table {table!r} for {cloud}; expected one of '
            f'{tables}.')
    if from_file:
        path = catalog_common.update_from_file(cloud, table, from_file)
    else:
        path = catalog_common.update_from_url(cloud, table, url)
    cat.reload()
    click.echo(f'Updated {path}')


@cli.command(name='cost-report')
def cost_report() -> None:
    """Estimated costs of all clusters ever launched."""
    rows = []
    for r in _sky().cost_report():
        cost = f"${r['cost']:.2f}" if r['cost'] is not None else '-'
        hours = r['duration_seconds'] / 3600
        rows.append((r['name'], f'{hours:.2f}h', cost,
                     'yes' if r['still_exists'] else 'no'))
    _print_table(('NAME', 'DURATION', 'COST', 'EXISTS'), rows)


@cli.group()
def storage() -> None:
    """Storage management."""


@storage.command(name='ls')
def storage_ls() -> None:
    rows = [(s['name'], s['status'].value, s['handle'].get('store', '-'))
            for s in _sky().storage_ls()]
    _print_table(('NAME', 'STATUS', 'STORE'), rows)


@storage.command(name='transfer')
@click.argument('src_url')
@click.argument('dst_url')
@click.option('--transfer-service', is_flag=True, default=False,
              help='S3->GCS only: server-side copy via the GCP Storage '
                   'Transfer Service instead of daisy-chaining through '
                   'this machine.')
def storage_transfer(src_url, dst_url, transfer_service) -> None:
    """Copy a bucket between clouds (gs:// <-> s3://)."""
    from skypilot_tpu.data import data_transfer
    if transfer_service:
        if not (src_url.startswith('s3://') and
                dst_url.startswith('gs://')):
            raise click.UsageError(
                '--transfer-service supports s3:// -> gs:// only.')
        src_bkt = src_url[len('s3://'):].rstrip('/')
        dst_bkt = dst_url[len('gs://'):].rstrip('/')
        if '/' in src_bkt or '/' in dst_bkt:
            raise click.UsageError(
                '--transfer-service copies whole buckets; prefix URLs '
                'are only supported by the default (gsutil) path.')
        data_transfer.s3_to_gcs_via_transfer_service(src_bkt, dst_bkt)
    else:
        data_transfer.transfer(src_url, dst_url)
    click.echo(f'Transferred {src_url} -> {dst_url}.')


@storage.command(name='delete')
@click.argument('names', nargs=-1, required=True)
@click.option('--yes', '-y', is_flag=True, default=False)
def storage_delete(names, yes) -> None:
    for name in names:
        if not yes:
            click.confirm(f'Delete storage {name!r}?', default=True,
                          abort=True)
        _sky().storage_delete(name)
        click.echo(f'Storage {name!r} deleted.')


@cli.group()
def jobs() -> None:
    """Managed jobs with automatic preemption recovery."""


@jobs.command(name='launch')
@click.argument('entrypoint', nargs=-1, required=True)
@click.option('--name', '-n', default=None)
@click.option('--detach-run', '-d', is_flag=True, default=False,
              help='Do not wait for the job to finish.')
@click.option('--remote-controller', '-r', is_flag=True, default=False,
              help='Run the recovery controller on a self-hosted '
                   'controller cluster (survives this client exiting).')
@_add_options(_RESOURCE_OPTIONS)
def jobs_launch(entrypoint, name, detach_run, remote_controller,
                **overrides) -> None:
    """Submit a managed job (auto-recovered on preemption)."""
    from skypilot_tpu.jobs import core as jobs_core
    task = _make_task(entrypoint, name=name, **overrides)
    if remote_controller:
        import time as time_lib

        import skypilot_tpu as sky
        from skypilot_tpu.jobs import remote as jobs_remote
        cluster, agent_job = jobs_remote.launch(task, name=name)
        click.echo(f'Managed job submitted to controller cluster '
                   f'{cluster!r} (controller job {agent_job}). Query '
                   f'with: sky jobs queue --remote-controller')
        if not detach_run:
            # The controller job's lifetime IS the managed job's
            # lifetime; wait for it like the local path waits.
            while True:
                status = sky.job_status(cluster, [agent_job])[agent_job]
                if status in ('SUCCEEDED', 'FAILED', 'FAILED_SETUP',
                              'FAILED_DRIVER', 'CANCELLED'):
                    break
                time_lib.sleep(5)
            click.echo(f'Managed job finished (controller job status: '
                       f'{status}).')
            if status != 'SUCCEEDED':
                sys.exit(1)
        return
    job_id = jobs_core.launch(task, name=name)
    click.echo(f'Managed job {job_id} submitted.')
    if not detach_run:
        while True:
            try:
                status = jobs_core.wait(job_id, timeout=3600)
                break
            except TimeoutError:
                continue  # still running; keep waiting
        click.echo(f'Managed job {job_id} finished: {status.value}')
        if status.is_failed():
            sys.exit(1)


@jobs.command(name='queue')
@click.option('--remote-controller', '-r', is_flag=True, default=False,
              help='Query the self-hosted controller cluster.')
def jobs_queue(remote_controller) -> None:
    """List managed jobs."""
    if remote_controller:
        from skypilot_tpu.jobs import remote as jobs_remote
        jobs_rows = jobs_remote.queue()
    else:
        from skypilot_tpu.jobs import core as jobs_core
        jobs_rows = jobs_core.queue()
    rows = []
    for j in jobs_rows:
        status_str = j['status'].value if hasattr(j['status'], 'value') \
            else str(j['status'])
        rows.append((str(j['job_id']), j['job_name'] or '-', status_str,
                     str(j.get('recovery_count', 0))))
    _print_table(('ID', 'NAME', 'STATUS', 'RECOVERIES'), rows)


@jobs.command(name='cancel')
@click.argument('job_ids', type=int, nargs=-1)
@click.option('--all', '-a', 'all_jobs', is_flag=True, default=False)
@click.option('--remote-controller', '-r', is_flag=True, default=False,
              help='Cancel on the self-hosted controller cluster.')
def jobs_cancel(job_ids, all_jobs, remote_controller) -> None:
    if remote_controller:
        from skypilot_tpu.jobs import remote as jobs_remote
        cancelled = jobs_remote.cancel(list(job_ids) or None, all_jobs)
    else:
        from skypilot_tpu.jobs import core as jobs_core
        cancelled = jobs_core.cancel(list(job_ids) or None, all_jobs)
    click.echo(f'Cancelled managed jobs: {cancelled}')


@jobs.command(name='logs')
@click.argument('job_id', type=int, required=False)
@click.option('--name', '-n', default=None)
@click.option('--follow/--no-follow', default=True)
@click.option('--controller', is_flag=True, default=False,
              help='Show the recovery controller log instead.')
@click.option('--remote-controller', is_flag=True, default=False,
              help='Fetch the controller EVENT log from the controller '
                   'cluster (one-shot; task run logs stream via '
                   '`sky logs <task-cluster>`).')
def jobs_logs(job_id, name, follow, controller,
              remote_controller) -> None:
    if remote_controller:
        if job_id is None or name is not None:
            raise click.UsageError(
                '--remote-controller takes a job id (not --name).')
        from skypilot_tpu.jobs import remote as jobs_remote
        click.echo(jobs_remote.tail_logs(job_id))
        return
    from skypilot_tpu.jobs import core as jobs_core
    out = jobs_core.tail_logs(job_id, name=name, controller=controller,
                              follow=follow and not controller)
    if out:
        click.echo(out)


@jobs.command(name='dashboard')
@click.option('--host', default='127.0.0.1', show_default=True)
@click.option('--port', '-p', default=None, type=int,
              help='Port to serve on (default 5050).')
def jobs_dashboard(host, port) -> None:
    """Serve the managed-jobs web dashboard (reference cli.py:3934)."""
    from skypilot_tpu.jobs import dashboard
    port = port if port is not None else dashboard.DEFAULT_PORT
    click.echo(f'Jobs dashboard: http://{host}:{port} (Ctrl-C to stop)')
    try:
        dashboard.serve_forever(host, port)
    except KeyboardInterrupt:
        pass


@cli.group()
def serve() -> None:
    """SkyServe-style multi-replica serving."""


@serve.command(name='up')
@click.argument('entrypoint', nargs=-1, required=True)
@click.option('--service-name', '-n', default=None)
@click.option('--remote-controller', is_flag=True, default=False,
              help='Run the service runtime on a controller cluster so '
                   'it survives this client (reference: serve '
                   'controller VM).')
@_add_options(_RESOURCE_OPTIONS)
def serve_up(entrypoint, service_name, remote_controller,
             **overrides) -> None:
    task = _make_task(entrypoint, **overrides)
    if remote_controller:
        from skypilot_tpu.serve import remote as serve_remote
        result = serve_remote.up(task, service_name)
        click.echo(
            f"Service {result['service_name']!r} deployed at "
            f"{result['endpoint']} (controller cluster "
            f"{result['controller_cluster']!r}). Query with: "
            'sky serve status --remote-controller')
        return
    from skypilot_tpu.serve import core as serve_core
    name, endpoint = serve_core.up(task, service_name)
    click.echo(f'Service {name!r} deployed at {endpoint}.')


@serve.command(name='status')
@click.argument('service_names', nargs=-1, required=False)
@click.option('--remote-controller', is_flag=True, default=False)
def serve_status(service_names, remote_controller) -> None:
    if remote_controller:
        from skypilot_tpu.serve import remote as serve_remote
        for s in serve_remote.status(list(service_names) or None):
            replicas = s.get('replica_info', [])
            ready = sum(1 for r in replicas
                        if str(r.get('status')) == 'READY')
            click.echo(f"{s['name']}\t{s.get('status')}\t"
                       f"{ready}/{len(replicas)} ready\t"
                       f"{s.get('endpoint')}")
        return
    from skypilot_tpu.serve import core as serve_core
    from skypilot_tpu.serve import serve_utils
    records = serve_core.status(list(service_names) or None)
    click.echo(serve_utils.format_service_table(records))
    for s in records:
        if s['replica_info']:
            click.echo(f'\nReplicas of {s["name"]!r}:')
            click.echo(serve_utils.format_replica_table(s['name']))


@serve.command(name='dashboard')
@click.option('--host', default='127.0.0.1', show_default=True)
@click.option('--port', '-p', default=None, type=int,
              help='Port to serve on (default 5051).')
def serve_dashboard(host, port) -> None:
    """Serve the SkyServe web dashboard (services + replicas).

    Beats the reference: it ships only a jobs dashboard.  The same
    snapshot is also mounted on every running controller at
    /services."""
    from skypilot_tpu.serve import dashboard
    port = port if port is not None else dashboard.DEFAULT_PORT
    click.echo(f'Serve dashboard: http://{host}:{port} '
               f'(Ctrl-C to stop)')
    try:
        dashboard.serve_forever(host, port)
    except KeyboardInterrupt:
        pass


@serve.command(name='update')
@click.argument('service_name', required=True)
@click.argument('entrypoint', nargs=-1, required=True)
@click.option('--remote-controller', is_flag=True, default=False)
@_TASK_NAME_OPTION
@_add_options(_RESOURCE_OPTIONS)
def serve_update(service_name, entrypoint, remote_controller,
                 **overrides) -> None:
    """Rolling-update a running service to a new task/spec."""
    task = _make_task(entrypoint, **overrides)
    if remote_controller:
        from skypilot_tpu.serve import remote as serve_remote
        version = serve_remote.update(task, service_name)
    else:
        from skypilot_tpu.serve import core as serve_core
        version = serve_core.update(task, service_name)
    click.echo(f'Service {service_name!r} updating to version {version}.')


@serve.command(name='logs')
@click.argument('service_name', required=True)
def serve_logs(service_name) -> None:
    """Show the service runtime log (controller + LB)."""
    from skypilot_tpu.serve import core as serve_core
    click.echo(serve_core.tail_logs(service_name))


@serve.command(name='down')
@click.argument('service_names', nargs=-1, required=False)
@click.option('--all', '-a', 'all_services', is_flag=True, default=False)
@click.option('--purge', is_flag=True, default=False)
@click.option('--yes', '-y', is_flag=True, default=False)
@click.option('--remote-controller', is_flag=True, default=False)
def serve_down(service_names, all_services, purge, yes,
               remote_controller) -> None:
    if not service_names and not all_services:
        raise click.UsageError('Provide service names or --all.')
    if not yes:
        target = ', '.join(service_names) if service_names else 'ALL'
        click.confirm(f'Tear down service(s) {target}?', default=True,
                      abort=True)
    if remote_controller:
        from skypilot_tpu.serve import remote as serve_remote
        downed = serve_remote.down(list(service_names) or None,
                                   all_services=all_services,
                                   purge=purge)
        click.echo(f'Torn down on controller: {downed}')
        return
    from skypilot_tpu.serve import core as serve_core
    serve_core.down(list(service_names) or None, all_services=all_services,
                    purge=purge)
    click.echo('Service(s) torn down.')


@cli.group()
def bench() -> None:
    """Benchmark one task across candidate resources ($/step)."""


@bench.command(name='launch')
@click.argument('entrypoint', nargs=-1, required=True)
@click.option('--benchmark', '-b', required=True, help='Benchmark name.')
@click.option('--candidate', '-c', 'candidates', multiple=True,
              required=True,
              help="Resource override, e.g. 'accelerators=tpu-v5e-8' "
                   "or 'accelerators=tpu-v6e-8,use_spot=true'. Repeat "
                   'for each candidate.')
def bench_launch(entrypoint, benchmark, candidates) -> None:
    from skypilot_tpu.benchmark import harness
    task = _make_task(entrypoint)
    parsed = []
    for cand in candidates:
        overrides = {}
        for kv in cand.split(','):
            if '=' not in kv or not kv.split('=', 1)[0].strip():
                raise click.UsageError(
                    f'bad --candidate entry {kv!r} in {cand!r}: '
                    "expected key=value (e.g. 'accelerators=tpu-v5e-8')")
            k, v = kv.split('=', 1)
            overrides[k.strip()] = (
                v.strip().lower() == 'true' if v.strip().lower() in
                ('true', 'false') else v.strip())
        parsed.append(overrides)
    clusters = harness.launch(task, parsed, benchmark)
    click.echo(f'Benchmark {benchmark!r} launched on: '
               f'{", ".join(clusters)}')


@bench.command(name='status')
@click.argument('benchmark', required=True)
def bench_status(benchmark) -> None:
    from skypilot_tpu.benchmark import harness
    rows = []
    for r in harness.status(benchmark):
        rows.append((
            r['cluster'], json.dumps(r['resources']), r['num_steps'],
            f"{r['secs_per_step']:.3f}" if r['secs_per_step'] else '-',
            f"${r['dollars_per_step']:.6f}"
            if r['dollars_per_step'] else '-'))
    _print_table(('CLUSTER', 'RESOURCES', 'STEPS', 'SEC/STEP', '$/STEP'),
                 rows)


@bench.command(name='down')
@click.argument('benchmark', required=True)
@click.option('--purge', is_flag=True, default=False)
@click.option('--yes', '-y', is_flag=True, default=False)
def bench_down(benchmark, purge, yes) -> None:
    from skypilot_tpu.benchmark import harness
    if not yes:
        click.confirm(f'Tear down benchmark {benchmark!r} clusters?',
                      default=True, abort=True)
    harness.down(benchmark, purge=purge)
    click.echo(f'Benchmark {benchmark!r} torn down.')


@bench.command(name='ls')
def bench_ls() -> None:
    """List recorded benchmarks (reference: `sky benchmark-ls`,
    cli.py:4723).  Records survive `bench down` — results stay
    queryable after the clusters are gone."""
    from skypilot_tpu.benchmark import state as bench_state
    rows = []
    for name in bench_state.get_benchmarks():
        runs = bench_state.get_runs(name)
        launched = min((r['launched_at'] for r in runs
                        if r['launched_at']), default=None)
        rows.append((
            name, len(runs),
            ', '.join(sorted(r['cluster'] for r in runs)) or '-',
            time.strftime('%Y-%m-%d %H:%M',
                          time.localtime(launched))
            if launched else '-'))
    _print_table(('BENCHMARK', 'CANDIDATES', 'CLUSTERS', 'LAUNCHED'),
                 rows)


@bench.command(name='delete')
@click.argument('benchmarks', nargs=-1, required=True)
@click.option('--yes', '-y', is_flag=True, default=False)
def bench_delete(benchmarks, yes) -> None:
    """Delete recorded benchmark results (reference:
    `sky benchmark-delete`, cli.py:5100).  Records only — clusters are
    torn down by `bench down`."""
    from skypilot_tpu.benchmark import state as bench_state
    known = set(bench_state.get_benchmarks())
    missing = [b for b in benchmarks if b not in known]
    if missing:
        raise click.UsageError(
            f'No such benchmark record(s): {", ".join(missing)}')
    if not yes:
        click.confirm(
            f'Delete benchmark record(s) {", ".join(benchmarks)}?',
            default=True, abort=True)
    for name in benchmarks:
        bench_state.delete_benchmark(name)
        click.echo(f'Deleted benchmark record {name!r}.')


def _print_table(headers: Tuple[str, ...], rows: List[Tuple]) -> None:
    if not rows:
        click.echo('(empty)')
        return
    widths = [max(len(str(h)), *(len(str(r[i])) for r in rows))
              for i, h in enumerate(headers)]
    click.echo('  '.join(str(h).ljust(w) for h, w in zip(headers, widths)))
    for row in rows:
        click.echo('  '.join(str(c).ljust(w) for c, w in zip(row, widths)))


@cli.group(name='local')
def local_group() -> None:
    """Deploy a local/on-prem Kubernetes cluster as a cloud
    (reference: `sky local`, cli.py:5246)."""


@local_group.command(name='up')
@click.option('--ips', 'ips_file', default=None,
              help='File with one IP per line: deploy k3s over SSH '
                   'onto these machines (first IP = server) instead '
                   'of a kind cluster on this host.')
@click.option('--ssh-user', default='root',
              help='SSH user for --ips mode.')
@click.option('--ssh-key-path', default=None,
              help='SSH private key for --ips mode.')
def local_up(ips_file, ssh_user, ssh_key_path) -> None:
    """Create a Kubernetes cluster: kind on this machine, or k3s over
    SSH onto --ips machines — then enable the kubernetes cloud."""
    import skypilot_tpu.check as check_lib
    from skypilot_tpu.utils import local_deploy
    if ips_file:
        ips = local_deploy.read_ips_file(ips_file)
        path, _ = local_deploy.up_remote(ips, ssh_user, ssh_key_path)
        click.echo(f'k3s cluster up on {len(ips)} machine(s); '
                   f'kubeconfig: {path}')
        click.echo(f'Run: export KUBECONFIG={path}')
        # The credential check must probe the cluster we just built,
        # not whatever context the user's default kubeconfig holds.
        prev = os.environ.get('KUBECONFIG')
        os.environ['KUBECONFIG'] = path
        try:
            check_lib.check(quiet=True, cloud_names=['kubernetes'])
        finally:
            if prev is None:
                os.environ.pop('KUBECONFIG', None)
            else:
                os.environ['KUBECONFIG'] = prev
    else:
        context = local_deploy.up_local()
        click.echo(f'kind cluster up (context {context}).')
        check_lib.check(quiet=True, cloud_names=['kubernetes'])


@local_group.command(name='down')
@click.option('--ips', 'ips_file', default=None,
              help='File with the IPs used at `local up --ips`.')
@click.option('--ssh-user', default='root')
@click.option('--ssh-key-path', default=None)
def local_down(ips_file, ssh_user, ssh_key_path) -> None:
    """Tear the `local up` cluster down."""
    import skypilot_tpu.check as check_lib
    from skypilot_tpu.utils import local_deploy
    if ips_file:
        ips = local_deploy.read_ips_file(ips_file)
        local_deploy.down_remote(ips, ssh_user, ssh_key_path)
        click.echo(f'k3s removed from {len(ips)} machine(s).')
    else:
        local_deploy.down_local()
        click.echo('kind cluster deleted.')
    # Drop the (now-dead) kubernetes entry from the enabled-clouds
    # cache so the optimizer stops proposing a deleted cluster.
    check_lib.check(quiet=True, cloud_names=['kubernetes'])


def main() -> None:
    try:
        cli()
    except exceptions.SkyTpuError as e:
        click.echo(f'Error: {e}', err=True)
        sys.exit(1)


if __name__ == '__main__':
    main()
