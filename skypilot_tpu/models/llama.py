"""Llama model family, TPU-first (flax + logical sharding + flash attention).

The reference ships Llama recipes that delegate modeling to torchtune /
vLLM (llm/llama-3_1-finetuning/lora.yaml, llm/llama-2 etc.); here the
model is first-party so the framework controls sharding layouts, remat and
kernels (SURVEY.md §7 hard part #6 — "requires MaxText-grade model code").

Design notes:
  - every parameter carries *logical* axis names via nn.with_partitioning;
    parallel/sharding.py maps them to mesh axes (fsdp/tensor/...)
  - attention runs on the Pallas flash kernel (ops/flash_attention) with
    bandwidth-optimal GQA — K/V stay at n_kv_heads end-to-end and the
    head-group broadcast happens inside the kernels/einsums
    (ops/grouped_attention) — and rotary embeddings; context-parallel
    ring attention slots in via `attention_impl='ring'`
  - layers are scanned (nn.scan) so compile time is O(1) in depth
  - activations/computation in bfloat16, params f32 (master), RMSNorm and
    softmax accumulate in f32
"""
from __future__ import annotations

import contextlib
import dataclasses
import threading
from typing import Any, Callable, Dict, Optional, Tuple

import flax.linen as nn
import jax
from jax.ad_checkpoint import checkpoint_name
import jax.numpy as jnp

from skypilot_tpu.ops import flash_attention as fa
from skypilot_tpu.ops import grouped_attention as ga
from skypilot_tpu.ops import paged_attention as pa
from skypilot_tpu.ops import ragged_prefill as rp


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    name: str
    vocab_size: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = True
    remat: bool = True
    # 'nothing' = recompute everything in backward (min memory);
    # 'save_attn' = keep attention outputs (skips recomputing the
    # seq-quadratic part — the right trade at long sequence lengths
    # whenever HBM allows).
    remat_policy: str = 'nothing'
    attention_impl: str = 'flash'   # flash | ring | reference
    # Sliding-window attention (Mistral-style): each token attends to
    # its last `sliding_window` positions (inclusive).  None = full
    # causal.  Applies to training (flash/reference) AND the decode
    # cache paths; not yet composable with ring/ulysses context
    # parallelism.
    sliding_window: Optional[int] = None
    # Autoregressive serving mode: attention keeps a KV cache in the
    # 'cache' variable collection (infer/engine.py drives it).
    decode: bool = False
    # Serving KV-cache storage dtype: 'auto' stores at `dtype`; 'int8'
    # stores rows as int8 + per-(kv-head, position) f32 absmax scales
    # (run_cached_attention) and reads through the fused-dequant
    # epilogue — halves decode cache traffic vs bf16.
    kv_cache_dtype: str = 'auto'
    # Paged serving KV cache (slot-mode continuous batching only):
    # kv_page_size > 0 stores the decode cache as a pool of
    # [kv_n_pages, kvh, kv_page_size, hd] physical pages plus a per-slot
    # block table, so decode HBM reads scale with each request's LIVE
    # context instead of max_seq_len, and prefix pages can be
    # refcount-shared between requests (infer/paging.py).  Page 0 is a
    # reserved null page.  0 = contiguous [B, kvh, max_seq_len, hd]
    # rows (the request-level engine always uses the contiguous
    # layout).
    kv_page_size: int = 0
    kv_n_pages: int = 0
    # Attach logical-axis metadata to params (nn.with_partitioning).
    # Disabled when modules are applied inside a shard_map manual region
    # (pipeline stages): flax's apply-time shape validation eval_shapes
    # the init fn, and a Partitioned box would then emit a sharding
    # constraint with logical names against the abstract manual mesh.
    partition_params: bool = True
    # LoRA finetuning (reference marquee recipe:
    # llm/llama-3_1-finetuning/lora.yaml via torchtune): rank 0 = off.
    # Adapters are ADDITIVE sibling params ('<proj>_lora'), so base
    # param paths are unchanged and a pretrained base checkpoint loads
    # through the params-only partial restore
    # (train/checkpoint.py restore_params_partial); train only the
    # adapters with trainer `train_only='lora'`.
    lora_rank: int = 0
    lora_alpha: float = 16.0
    lora_targets: Tuple[str, ...] = ('q_proj', 'k_proj', 'v_proj',
                                     'o_proj')

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


CONFIGS: Dict[str, LlamaConfig] = {
    # Debug config: small but structurally identical (GQA, scan, remat).
    'llama-tiny': LlamaConfig('llama-tiny', vocab_size=512, dim=256,
                              n_layers=2, n_heads=2, n_kv_heads=1,
                              ffn_dim=512, max_seq_len=512,
                              scan_layers=True),
    'llama3-8b': LlamaConfig('llama3-8b'),
    'llama3-70b': LlamaConfig('llama3-70b', dim=8192, n_layers=80,
                              n_heads=64, n_kv_heads=8, ffn_dim=28672),
    'llama3.2-1b': LlamaConfig('llama3.2-1b', dim=2048, n_layers=16,
                               n_heads=32, n_kv_heads=8, ffn_dim=8192),
    'llama2-7b': LlamaConfig('llama2-7b', vocab_size=32000, dim=4096,
                             n_layers=32, n_heads=32, n_kv_heads=32,
                             ffn_dim=11008, rope_theta=10000.0,
                             max_seq_len=4096),
    # Mistral = Llama arch + sliding-window attention (window 4096),
    # which is what makes its 32k context affordable: attention
    # compute/KV reads are O(S*W) not O(S^2).
    'mistral-7b': LlamaConfig('mistral-7b', vocab_size=32000, dim=4096,
                              n_layers=32, n_heads=32, n_kv_heads=8,
                              ffn_dim=14336, rope_theta=10000.0,
                              max_seq_len=32768, sliding_window=4096),
}


def get_config(name: str, **overrides: Any) -> LlamaConfig:
    if name not in CONFIGS:
        raise ValueError(f'Unknown llama config {name!r}; '
                         f'available: {sorted(CONFIGS)}')
    return dataclasses.replace(CONFIGS[name], **overrides)


# ---------------------------------------------------------------------------
# shared forward pieces — used by Llama.__call__ AND the pipelined
# trainer path (train/trainer.py _pipelined_apply), so the two forwards
# cannot diverge on embed/position/head math.
# ---------------------------------------------------------------------------
def default_positions(tokens: jax.Array) -> jax.Array:
    return jnp.broadcast_to(
        jnp.arange(tokens.shape[1], dtype=jnp.int32)[None], tokens.shape)


def embed_lookup(cfg: 'LlamaConfig', tok_embed: jax.Array,
                 tokens: jax.Array) -> jax.Array:
    return jnp.take(tok_embed.astype(cfg.dtype), tokens, axis=0)


def apply_final_head(cfg: 'LlamaConfig', final_norm_params,
                     lm_head_params, x: jax.Array) -> jax.Array:
    """Final RMSNorm + lm_head on raw param trees (pipelined path).
    Must mirror the inline modules at the end of Llama.__call__."""
    x = RMSNorm(cfg.norm_eps, cfg.dtype, cfg.partition_params).apply(
        {'params': final_norm_params}, x)
    return nn.DenseGeneral(
        cfg.vocab_size, use_bias=False, dtype=jnp.float32,
        param_dtype=cfg.param_dtype).apply({'params': lm_head_params}, x)


# ---------------------------------------------------------------------------
# building blocks
# ---------------------------------------------------------------------------
def _partitioned_init(init_fn: Callable, names: Tuple[Optional[str], ...],
                      partition: bool = True):
    return nn.with_partitioning(init_fn, names) if partition else init_fn


class RMSNorm(nn.Module):
    eps: float
    dtype: Any
    partition: bool = True
    # Gemma convention: weight stored as an offset from 1 and
    # initialized to zero ((1 + scale) * x̂).
    plus_one: bool = False

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        init = (nn.initializers.zeros if self.plus_one
                else nn.initializers.ones)
        scale = self.param('scale',
                           _partitioned_init(init, ('embed',),
                                             self.partition),
                           (x.shape[-1],), jnp.float32)
        if self.plus_one:
            scale = 1.0 + scale
        xf = x.astype(jnp.float32)
        norm = jax.lax.rsqrt(
            jnp.mean(xf * xf, axis=-1, keepdims=True) + self.eps)
        return (xf * norm * scale).astype(self.dtype)


def apply_rope(x: jax.Array, positions: jax.Array,
               theta: float) -> jax.Array:
    """Rotary embeddings on [B, H, S, D] (interleaved-pairs-free "split
    half" convention, matching Llama)."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[:, None, :, None].astype(jnp.float32) * freqs
    cos = jnp.cos(angles)
    sin = jnp.sin(angles)
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                          axis=-1)
    return out.astype(x.dtype)


class LoraAdapter(nn.Module):
    """Low-rank additive delta for one projection: (x @ A) @ B scaled
    by alpha/rank.  B starts at zero, so a fresh adapter is a no-op and
    finetuning starts exactly at the base model."""
    rank: int
    alpha: float
    features: Tuple[int, ...]
    dtype: Any
    param_dtype: Any
    partition: bool = True

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        flat = 1
        for f in self.features:
            flat *= f
        a = self.param(
            'a',
            _partitioned_init(nn.initializers.normal(1.0 / self.rank),
                              ('embed_fsdp', None), self.partition),
            (x.shape[-1], self.rank), self.param_dtype)
        b = self.param(
            'b',
            _partitioned_init(nn.initializers.zeros, (None, None),
                              self.partition),
            (self.rank, flat), self.param_dtype)
        delta = (x.astype(self.dtype) @ a.astype(self.dtype)) \
            @ b.astype(self.dtype)
        delta = delta * (self.alpha / self.rank)
        return delta.reshape(*x.shape[:-1], *self.features)


def maybe_lora(cfg, name: str, x: jax.Array, y: jax.Array,
               features) -> jax.Array:
    """Add the LoRA delta for projection `name` when enabled."""
    if not getattr(cfg, 'lora_rank', 0) or \
            name not in getattr(cfg, 'lora_targets', ()):
        return y
    feats = features if isinstance(features, tuple) else (features,)
    return y + LoraAdapter(cfg.lora_rank, cfg.lora_alpha, feats,
                           cfg.dtype, cfg.param_dtype,
                           cfg.partition_params,
                           name=f'{name}_lora')(x)


_SLOT_MODE = threading.local()


@contextlib.contextmanager
def kv_read_bucket(n: Optional[int]):
    """Cap slot-mode decode attention READS to the first `n` cache
    positions (a static trace-time value; the engine rounds the
    deepest live cursor up to a bucket and compiles one decode step
    per bucket).  Writes still target the full cache; positions beyond
    the deepest cursor are unrevealed, so numerics are identical —
    this only cuts HBM traffic while contexts are short."""
    prev = getattr(_SLOT_MODE, 'kv_bucket', None)
    _SLOT_MODE.kv_bucket = n
    try:
        yield
    finally:
        _SLOT_MODE.kv_bucket = prev


@contextlib.contextmanager
def decode_kernel(kind: str):
    """Select the paged decode-attention implementation for calls
    traced under this context (a static trace-time choice, like
    slot_mode): 'fused' runs the Pallas kernel that walks the block
    table in-kernel (ops/paged_attention — interpreter mode off-TPU),
    'xla' keeps the gather_pages + grouped-einsum path.  The engine
    resolves its --decode-kernel=auto flag to one of the two and wraps
    its jitted decode/verify CALLS in this context; outside it the XLA
    path is always used."""
    if kind not in ('fused', 'xla'):
        raise ValueError(
            f"decode_kernel must be 'fused' or 'xla', got {kind!r}")
    prev = getattr(_SLOT_MODE, 'decode_kernel', 'xla')
    _SLOT_MODE.decode_kernel = kind
    try:
        yield
    finally:
        _SLOT_MODE.decode_kernel = prev


@contextlib.contextmanager
def prefill_kernel(kind: str):
    """Select the chunked-prefill attention implementation for calls
    traced under this context (the prefill sibling of decode_kernel):
    'fused' runs the Pallas ragged-prefill kernel that streams the
    live cache prefix page-by-page with in-kernel cursor-base causal
    masking (ops/ragged_prefill — interpreter mode off-TPU), 'xla'
    keeps the sliced-prefix + grouped-einsum path.  The engine
    resolves its --prefill-kernel=auto flag to one of the two and
    wraps its jitted prefill CALLS in this context; outside it the XLA
    path — the permanent fallback and parity oracle — is always
    used."""
    if kind not in ('fused', 'xla'):
        raise ValueError(
            f"prefill_kernel must be 'fused' or 'xla', got {kind!r}")
    prev = getattr(_SLOT_MODE, 'prefill_kernel', 'xla')
    _SLOT_MODE.prefill_kernel = kind
    try:
        yield
    finally:
        _SLOT_MODE.prefill_kernel = prev


@contextlib.contextmanager
def slot_mode():
    """Enable per-row cache cursors in run_cached_attention for calls
    traced under this context (ContinuousBatchingEngine wraps its jit
    CALLS in it — the flag is captured at trace time, so each engine's
    compiled steps keep their mode forever).  The request-level engine
    never enters it and keeps the global-cursor fast path."""
    prev = getattr(_SLOT_MODE, 'on', False)
    _SLOT_MODE.on = True
    try:
        yield
    finally:
        _SLOT_MODE.on = prev


def _verify_positions(kv_mask: jax.Array, s: int, max_len: int):
    """Per-row write positions for a multi-token slot forward.

    Speculative verify scores s = k+1 tokens (the pending token plus k
    draft proposals) in one forward.  The engine reveals ONLY the
    pending token's slot before the call (same protocol as one-token
    decode), so the row's write base is its highest revealed slot and
    query j writes (and may attend to) positions base..base+j.  The
    proposals' slots are NOT pre-revealed: acceptance reveals just the
    committed prefix afterwards, so a rejected suffix's K/V stays
    unrevealed garbage that a later verify overwrites in place —
    rollback is mask truncation, never a tensor copy.  Returns
    (base [B], pos [B, S]) with pos possibly exceeding max_len-1 for
    rows near the end of their budget (callers drop/redirect those
    writes; visibility never reaches them).
    """
    base = jnp.max(
        jnp.where(kv_mask, jnp.arange(max_len, dtype=jnp.int32), 0),
        axis=-1)                                   # [B]
    pos = base[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
    return base, pos


def _verify_mask(kv_mask: jax.Array, base: jax.Array, s: int,
                 read_len: int, window: Optional[int]) -> jax.Array:
    """[B, 1, S, read_len] visibility for a multi-token slot forward:
    query j sees every previously revealed slot plus the in-flight
    window base..base+j (its own position and the proposals before
    it)."""
    slots = jnp.arange(read_len, dtype=jnp.int32)
    qpos = base[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
    new_vis = ((slots[None, None, :] >= base[:, None, None]) &
               (slots[None, None, :] <= qpos[:, :, None]))
    visible = kv_mask[:, None, :read_len] | new_vis
    if window is not None:
        visible = visible & (
            slots[None, None, :] >= qpos[:, :, None] - window + 1)
    return visible[:, None]


def _paged_slot_attention(module: nn.Module, q: jax.Array,
                          k: jax.Array, v: jax.Array,
                          kv_mask: jax.Array, *, kvh: int, max_len: int,
                          dtype: Any, window: Optional[int],
                          quant: bool, page_size: int,
                          n_pages: int) -> jax.Array:
    """Slot-mode decode against the PAGED cache (PagedAttention layout).

    The cache is a pool of physical pages [n_pages, kvh, page_size, hd]
    (int8 pools carry sibling f32 scale pools) shared by every slot;
    each slot's 'block_table' row maps its logical page i (cache
    positions [i*ps, (i+1)*ps)) to a physical page.  Page 0 is a
    reserved NULL page: unallocated/evicted table entries point there,
    so a dead row's write (contiguous slot mode's "harmless rewrite")
    lands in the null page instead of scribbling into a page that may
    since belong to another request, and out-of-range gathers read
    garbage that kv_mask hides.  Reads gather only the pages under the
    engine's bucketed high-water mark (kv_read_bucket), so per-step HBM
    traffic tracks allocated live context, not max_seq_len — and
    prefix pages refcount-shared between slots (infer/paging.py) are
    read through each sharer's table without ever being duplicated.
    """
    b, h, s, hd = q.shape
    ps = page_size
    if max_len % ps:
        raise ValueError(
            f'kv_page_size ({ps}) must divide max_seq_len ({max_len})')
    if n_pages < 2:
        raise ValueError(
            f'kv_n_pages must be >= 2 (page 0 is the reserved null '
            f'page), got {n_pages}')
    pages_per_slot = max_len // ps
    cache_dtype = jnp.int8 if quant else dtype
    page_k = module.variable('cache', 'page_key', jnp.zeros,
                             (n_pages, kvh, ps, hd), cache_dtype)
    page_v = module.variable('cache', 'page_value', jnp.zeros,
                             (n_pages, kvh, ps, hd), cache_dtype)
    if quant:
        pk_scale = module.variable('cache', 'page_key_scale',
                                   jnp.zeros, (n_pages, kvh, ps, 1),
                                   jnp.float32)
        pv_scale = module.variable('cache', 'page_value_scale',
                                   jnp.zeros, (n_pages, kvh, ps, 1),
                                   jnp.float32)
    table = module.variable('cache', 'block_table', jnp.zeros,
                            (b, pages_per_slot), jnp.int32)
    cursor = module.variable('cache', 'cache_index',
                             lambda: jnp.zeros((), jnp.int32))
    brange = jnp.arange(b)
    if s == 1:
        # Write position: the row's highest revealed kv_mask slot (same
        # rule as the contiguous slot branch); the block table
        # translates it to (physical page, in-page offset).
        write_pos = jnp.max(
            jnp.where(kv_mask, jnp.arange(max_len, dtype=jnp.int32), 0),
            axis=-1)                               # [B]
        phys = table.value[brange, write_pos // ps]    # [B]
        off = write_pos % ps
        if quant:
            kq, ks = ga.quantize_int8_rows(k[:, :, 0, :])  # [b,kvh,hd]
            vq, vs = ga.quantize_int8_rows(v[:, :, 0, :])
            page_k.value = page_k.value.at[phys, :, off, :].set(kq)
            page_v.value = page_v.value.at[phys, :, off, :].set(vq)
            pk_scale.value = pk_scale.value.at[phys, :, off, :].set(ks)
            pv_scale.value = pv_scale.value.at[phys, :, off, :].set(vs)
        else:
            page_k.value = page_k.value.at[phys, :, off, :].set(
                k[:, :, 0, :].astype(dtype))
            page_v.value = page_v.value.at[phys, :, off, :].set(
                v[:, :, 0, :].astype(dtype))
    else:
        # Multi-token slot decode (speculative verify): see the
        # contiguous branch in run_cached_attention for the base /
        # visibility rule.  Positions past the row's allocated pages
        # (or past max_len) are redirected to the reserved null page —
        # the paged twin of the contiguous branch's dropped writes.
        base, pos = _verify_positions(kv_mask, s, max_len)
        lp = jnp.minimum(pos // ps, pages_per_slot - 1)
        phys = table.value[brange[:, None], lp]        # [B, S]
        phys = jnp.where(pos < max_len, phys, 0)
        off = pos % ps
        if quant:
            kq, ks = ga.quantize_int8_rows(k)      # [b,kvh,s,hd/1]
            vq, vs = ga.quantize_int8_rows(v)
            page_k.value = page_k.value.at[phys, :, off, :].set(
                kq.transpose(0, 2, 1, 3))
            page_v.value = page_v.value.at[phys, :, off, :].set(
                vq.transpose(0, 2, 1, 3))
            pk_scale.value = pk_scale.value.at[phys, :, off, :].set(
                ks.transpose(0, 2, 1, 3))
            pv_scale.value = pv_scale.value.at[phys, :, off, :].set(
                vs.transpose(0, 2, 1, 3))
        else:
            page_k.value = page_k.value.at[phys, :, off, :].set(
                k.astype(dtype).transpose(0, 2, 1, 3))
            page_v.value = page_v.value.at[phys, :, off, :].set(
                v.astype(dtype).transpose(0, 2, 1, 3))
    cursor.value = cursor.value + s
    # Static page-granular read window: the engine's kv_read_bucket
    # high-water mark, rounded up to whole pages.  Pages past it are
    # unrevealed for every active row, so the truncation is exact.
    bucket = getattr(_SLOT_MODE, 'kv_bucket', None)
    read_len = bucket if (bucket is not None
                          and bucket < max_len) else max_len
    n_read = -(-read_len // ps)
    read_len = n_read * ps
    tbl = table.value[:, :n_read]
    if s == 1:
        visible = kv_mask
        if window is not None:
            visible = visible & (
                jnp.arange(max_len)[None, :] >= write_pos[:, None]
                - window + 1)
        mask = visible[:, None, None, :read_len]
    else:
        mask = _verify_mask(kv_mask, base, s, read_len, window)
    if getattr(_SLOT_MODE, 'decode_kernel', 'xla') == 'fused':
        # Fused Pallas path (ops/paged_attention): the block table
        # rides in as a scalar-prefetch operand and pages stream
        # pool -> VMEM one tile at a time — no gathered contiguous
        # K/V/scale copies ever hit HBM.  The mask already encodes
        # every visibility rule (revealed slots, verify windows,
        # sliding window, null-page entries), so semantics are shared
        # with the XLA oracle below by construction.
        return pa.paged_decode_attention(
            q, page_k.value, page_v.value, tbl, mask,
            scale=hd ** -0.5, probs_dtype=dtype,
            key_scale=pk_scale.value if quant else None,
            value_scale=pv_scale.value if quant else None)
    keys = ga.gather_pages(page_k.value, tbl)
    values = ga.gather_pages(page_v.value, tbl)
    if quant:
        k_sc = ga.gather_pages(pk_scale.value, tbl)
        v_sc = ga.gather_pages(pv_scale.value, tbl)
        return ga.quantized_grouped_attention(
            q, keys, k_sc, values, v_sc, mask, scale=hd ** -0.5,
            probs_dtype=dtype)
    return ga.grouped_attention(q, keys, values, mask,
                                scale=hd ** -0.5, probs_dtype=dtype)


def run_cached_attention(module: nn.Module, q: jax.Array, k: jax.Array,
                         v: jax.Array,
                         kv_mask: Optional[jax.Array], *,
                         n_kv_heads: int, max_seq_len: int,
                         dtype: Any,
                         window: Optional[int] = None,
                         kv_cache_dtype: str = 'auto',
                         page_size: int = 0,
                         n_pages: int = 0) -> jax.Array:
    """Attention against the KV cache (serving) — shared by every
    family (Llama/Gemma via llama.Attention, GPT-2's MHA).

    The cache is written at the global slot cursor `cache_index`
    (same for every row); per-row validity — right-padded prompts,
    finished rows — is carried by `kv_mask` [B, max_seq_len], so
    slots and rope positions may disagree for padded rows without
    affecting valid tokens.  Returns [B, S, H, hd].

    kv_cache_dtype='int8' stores K/V rows as int8 with per-(kv-head,
    position) f32 absmax scales in sibling 'cache' leaves
    cached_{key,value}_scale [B, kvh, max_len, 1].  Writes quantize
    through the SAME `.at[]`/dynamic_update_slice paths (scale leaves
    share the cache's leading [B, kvh, pos] layout, so slot cursors,
    chunked prefill, and the engines' ndim-based insert/sharding all
    compose); reads go through the fused-dequant epilogue
    (ops/grouped_attention.quantized_grouped_attention), which never
    materializes a float copy of the cache.
    """
    if kv_cache_dtype not in ('auto', 'int8'):
        raise ValueError(
            f'kv_cache_dtype must be "auto" or "int8", '
            f'got {kv_cache_dtype!r}')
    quant = kv_cache_dtype == 'int8'
    b, h, s, hd = q.shape
    kvh = n_kv_heads
    max_len = max_seq_len
    slot = (kv_mask is not None
            and getattr(_SLOT_MODE, 'on', False))
    if page_size > 0 and slot:
        # Paged layout exists only for the slot-mode decode batch; the
        # batch-1 chunked-prefill cache stays contiguous (its pages
        # are scattered into the pool by the engine's paged insert).
        return _paged_slot_attention(
            module, q, k, v, kv_mask, kvh=kvh, max_len=max_len,
            dtype=dtype, window=window, quant=quant,
            page_size=page_size, n_pages=n_pages)
    cache_dtype = jnp.int8 if quant else dtype
    cached_k = module.variable('cache', 'cached_key', jnp.zeros,
                               (b, kvh, max_len, hd), cache_dtype)
    cached_v = module.variable('cache', 'cached_value', jnp.zeros,
                               (b, kvh, max_len, hd), cache_dtype)
    if quant:
        # Zero-init scales dequantize padding to exact zeros; masked
        # positions never reach the softmax anyway.
        k_scale = module.variable('cache', 'cached_key_scale',
                                  jnp.zeros, (b, kvh, max_len, 1),
                                  jnp.float32)
        v_scale = module.variable('cache', 'cached_value_scale',
                                  jnp.zeros, (b, kvh, max_len, 1),
                                  jnp.float32)
    cursor = module.variable('cache', 'cache_index',
                             lambda: jnp.zeros((), jnp.int32))
    idx = cursor.value
    if slot:
        # Slot-mode decode (continuous batching): each row's write
        # position is its highest *revealed* kv_mask slot — the engine
        # reveals the new token's slot before this forward, so rows at
        # different decode depths (different prompts admitted at
        # different times) share one step.  Visibility is kv_mask
        # alone; the global-cursor causal term would be wrong when
        # rows disagree.  Rows whose mask is untouched this step
        # (finished/empty slots) rewrite their last revealed slot with
        # a dead token's K/V — harmless: their outputs are discarded
        # and re-admission re-prefills the slot.
        brange = jnp.arange(b)
        if s == 1:
            write_pos = jnp.max(
                jnp.where(kv_mask,
                          jnp.arange(max_len, dtype=jnp.int32), 0),
                axis=-1)                           # [B]
            if quant:
                kq, ks = ga.quantize_int8_rows(k[:, :, 0, :])
                vq, vs = ga.quantize_int8_rows(v[:, :, 0, :])
                cached_k.value = cached_k.value.at[
                    brange, :, write_pos, :].set(kq)
                cached_v.value = cached_v.value.at[
                    brange, :, write_pos, :].set(vq)
                k_scale.value = k_scale.value.at[
                    brange, :, write_pos, :].set(ks)
                v_scale.value = v_scale.value.at[
                    brange, :, write_pos, :].set(vs)
            else:
                cached_k.value = cached_k.value.at[
                    brange, :, write_pos, :].set(
                        k[:, :, 0, :].astype(dtype))
                cached_v.value = cached_v.value.at[
                    brange, :, write_pos, :].set(
                        v[:, :, 0, :].astype(dtype))
        else:
            # Multi-token slot decode (speculative verify): positions
            # base..base+s-1 are written WITHOUT being revealed; see
            # _verify_positions.  mode='drop' discards writes past
            # max_len for rows at the end of their budget (their pad
            # queries' outputs are rolled back by acceptance anyway).
            base, pos = _verify_positions(kv_mask, s, max_len)
            bcol = brange[:, None]
            if quant:
                kq, ks = ga.quantize_int8_rows(k)  # [b,kvh,s,hd/1]
                vq, vs = ga.quantize_int8_rows(v)
                cached_k.value = cached_k.value.at[bcol, :, pos, :].set(
                    kq.transpose(0, 2, 1, 3), mode='drop')
                cached_v.value = cached_v.value.at[bcol, :, pos, :].set(
                    vq.transpose(0, 2, 1, 3), mode='drop')
                k_scale.value = k_scale.value.at[bcol, :, pos, :].set(
                    ks.transpose(0, 2, 1, 3), mode='drop')
                v_scale.value = v_scale.value.at[bcol, :, pos, :].set(
                    vs.transpose(0, 2, 1, 3), mode='drop')
            else:
                cached_k.value = cached_k.value.at[bcol, :, pos, :].set(
                    k.astype(dtype).transpose(0, 2, 1, 3), mode='drop')
                cached_v.value = cached_v.value.at[bcol, :, pos, :].set(
                    v.astype(dtype).transpose(0, 2, 1, 3), mode='drop')
        cursor.value = idx + s
        if s == 1:
            visible = kv_mask
            if window is not None:
                # A row's slots are its tokens in order, so windowing
                # by slot index relative to the newest (write) slot
                # matches training's position window exactly.
                visible = visible & (
                    jnp.arange(max_len)[None, :] >=
                    write_pos[:, None] - window + 1)
            mask = visible[:, None, None, :]
        # Static read-window over the live prefix of the cache (see
        # kv_read_bucket) — everything past it is unrevealed for
        # active rows, so slicing keys/values/mask is exact.  The
        # shared epilogue below handles the (possibly shortened) set.
        bucket = getattr(_SLOT_MODE, 'kv_bucket', None)
        read_len = bucket if (bucket is not None
                              and bucket < max_len) else max_len
        keys = cached_k.value[:, :, :read_len]
        values = cached_v.value[:, :, :read_len]
        if quant:
            k_sc = k_scale.value[:, :, :read_len]
            v_sc = v_scale.value[:, :, :read_len]
        if s == 1:
            mask = mask[:, :, :, :read_len]
        else:
            mask = _verify_mask(kv_mask, base, s, read_len, window)
    else:
        if quant:
            kq, ks = ga.quantize_int8_rows(k)      # [b,kvh,s,hd/1]
            vq, vs = ga.quantize_int8_rows(v)
            cached_k.value = jax.lax.dynamic_update_slice(
                cached_k.value, kq, (0, 0, idx, 0))
            cached_v.value = jax.lax.dynamic_update_slice(
                cached_v.value, vq, (0, 0, idx, 0))
            k_scale.value = jax.lax.dynamic_update_slice(
                k_scale.value, ks, (0, 0, idx, 0))
            v_scale.value = jax.lax.dynamic_update_slice(
                v_scale.value, vs, (0, 0, idx, 0))
        else:
            cached_k.value = jax.lax.dynamic_update_slice(
                cached_k.value, k.astype(dtype), (0, 0, idx, 0))
            cached_v.value = jax.lax.dynamic_update_slice(
                cached_v.value, v.astype(dtype), (0, 0, idx, 0))
        cursor.value = idx + s
        # Chunked-prefill read cap (kv_read_bucket, same machinery as
        # slot-mode decode): the engine guarantees bucket >= idx + s,
        # and the causal term below zeroes every column >= idx + s, so
        # slicing keys/values/mask to the bucket is exact — prefill
        # chunk attention reads the live prefix, not max_seq_len.
        bucket = getattr(_SLOT_MODE, 'kv_bucket', None)
        read_len = bucket if (bucket is not None
                              and bucket < max_len) else max_len
        if (page_size > 0
                and getattr(_SLOT_MODE, 'prefill_kernel',
                            'xla') == 'fused'):
            # Fused ragged prefill (ops/ragged_prefill): stream the
            # live prefix from the cache one page-shaped tile at a
            # time, with the causal mask computed in-kernel against
            # the chunk's cursor base — no [b, kvh, read_len, hd]
            # sliced copy and no [s, read_len] mask tensor in HBM.
            # The identity table walks the contiguous cache as logical
            # pages; columns in the n_read*ps round-up past read_len
            # sit at positions >= idx + s and are causally dead, so
            # page-granular reads are exact.
            n_read = -(-read_len // page_size)
            tbl = jnp.broadcast_to(
                jnp.arange(n_read, dtype=jnp.int32)[None],
                (b, n_read))
            vis = (kv_mask if kv_mask is not None
                   else jnp.ones((b, max_len), bool))
            if vis.shape[1] < max_len:
                # Padded columns sit at positions >= idx + s (the
                # engine's read bucket covers the mask) — causally
                # dead either way, so padding False is exact.
                vis = jnp.pad(
                    vis, ((0, 0), (0, max_len - vis.shape[1])))
            return rp.ragged_prefill_attention(
                q, cached_k.value, cached_v.value, tbl, idx, vis,
                scale=hd ** -0.5, probs_dtype=dtype,
                page_size=page_size, window=window,
                key_scale=k_scale.value if quant else None,
                value_scale=v_scale.value if quant else None)
        slots = jnp.arange(read_len)
        rows = idx + jnp.arange(s)
        causal = slots[None, :] <= rows[:, None]
        if window is not None:
            causal &= slots[None, :] >= rows[:, None] - window + 1
        mask = causal[None, None]                  # [1,1,s,read]
        if kv_mask is not None:
            mask = mask & kv_mask[:, None, None, :read_len]
        keys = cached_k.value[:, :, :read_len]
        values = cached_v.value[:, :, :read_len]
        if quant:
            k_sc = k_scale.value[:, :, :read_len]
            v_sc = v_scale.value[:, :, :read_len]
    # Grouped epilogue: the cache stays [B, kvh, read_len, hd] — the
    # head-group broadcast happens inside the einsum, never in HBM
    # (ops/grouped_attention.py).  The scale intentionally uses q's
    # LAST dim: DeepSeek's absorbed decode pre-multiplies q so this
    # lands on the true qk_head_dim scale (models/deepseek.py).
    if quant:
        return ga.quantized_grouped_attention(
            q, keys, k_sc, values, v_sc, mask, scale=hd ** -0.5,
            probs_dtype=dtype)
    return ga.grouped_attention(q, keys, values, mask,
                                scale=hd ** -0.5, probs_dtype=dtype)


class Attention(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x: jax.Array, positions: jax.Array,
                 kv_mask: Optional[jax.Array] = None) -> jax.Array:
        cfg = self.config
        # Qwen-style families put biases on Q/K/V (never O) — a config
        # knob so the whole attention stack stays shared.
        qkv_bias = getattr(cfg, 'attention_bias', False)
        dense = lambda features, names, name, use_bias=False: \
            nn.DenseGeneral(  # noqa: E731
                features, axis=-1, use_bias=use_bias, name=name,
                dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                kernel_init=_partitioned_init(
                    nn.initializers.normal(
                        0.02 / (2 * cfg.n_layers) ** 0.5
                        if name == 'o_proj' else 0.02), names,
                    cfg.partition_params))
        b, s, _ = x.shape
        h, kv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
        q = maybe_lora(cfg, 'q_proj', x,
                       dense((h, hd), ('embed_fsdp', 'heads', 'head_dim'),
                             'q_proj', qkv_bias)(x), (h, hd))
        k = maybe_lora(cfg, 'k_proj', x,
                       dense((kv, hd),
                             ('embed_fsdp', 'kv_heads', 'head_dim'),
                             'k_proj', qkv_bias)(x), (kv, hd))
        v = maybe_lora(cfg, 'v_proj', x,
                       dense((kv, hd),
                             ('embed_fsdp', 'kv_heads', 'head_dim'),
                             'v_proj', qkv_bias)(x), (kv, hd))
        # [B, S, H, hd] -> [B, H, S, hd]
        q = jnp.transpose(q, (0, 2, 1, 3))
        k = jnp.transpose(k, (0, 2, 1, 3))
        v = jnp.transpose(v, (0, 2, 1, 3))
        q = apply_rope(q, positions, cfg.rope_theta)
        k = apply_rope(k, positions, cfg.rope_theta)
        if cfg.decode:
            out = self._cached_attention(q, k, v, kv_mask)
            flat = out.reshape(b, s, h * hd)
            return maybe_lora(
                cfg, 'o_proj', flat,
                dense(cfg.dim, ('heads', 'embed_fsdp'), 'o_proj')(flat),
                cfg.dim)
        # GQA k/v stay at n_kv_heads: the flash kernel maps group
        # members onto shared kv blocks via its BlockSpec index maps,
        # the XLA fallback uses the grouped einsum, and the ring
        # rotates [B, kvh, S/c, d] chunks (h/kvh-fold less ICI
        # traffic).  No repeat ever materializes [B, H, S, d] K/V.
        # Duck-typed families (Gemma/Qwen share this module)
        # may not declare the field.
        window = getattr(cfg, 'sliding_window', None)
        if cfg.attention_impl == 'flash':
            out = fa.flash_attention(q, k, v, None, True,
                                     fa.DEFAULT_BLOCK_Q,
                                     fa.DEFAULT_BLOCK_KV, window)
        elif cfg.attention_impl in ('ring', 'ulysses'):
            # Windowed ring: static distance-bounded loop — chunks
            # beyond the window are neither computed nor rotated
            # (ops/ring_attention.py _ring_fwd_loop_windowed).
            from skypilot_tpu.ops import ring_attention
            out = ring_attention.context_parallel_attention(
                q, k, v, impl=cfg.attention_impl, window=window)
        else:
            out = fa.mha_reference(q, k, v, window=window)
        # Named so remat_policy='save_attn' can keep it (skipping the
        # O(s^2) recompute in the backward pass).
        out = checkpoint_name(out, 'attn_out')
        out = jnp.transpose(out, (0, 2, 1, 3)).reshape(b, s, h * hd)
        proj = nn.DenseGeneral(
            cfg.dim, use_bias=False, name='o_proj', dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=_partitioned_init(
                nn.initializers.normal(0.02 / (2 * cfg.n_layers) ** 0.5),
                ('heads', 'embed_fsdp'), cfg.partition_params))(out)
        return maybe_lora(cfg, 'o_proj', out, proj, cfg.dim)

    def _cached_attention(self, q: jax.Array, k: jax.Array,
                          v: jax.Array,
                          kv_mask: Optional[jax.Array]) -> jax.Array:
        cfg = self.config
        return run_cached_attention(self, q, k, v, kv_mask,
                                    n_kv_heads=cfg.n_kv_heads,
                                    max_seq_len=cfg.max_seq_len,
                                    dtype=cfg.dtype,
                                    window=getattr(
                                        cfg, 'sliding_window',
                                        None),
                                    kv_cache_dtype=getattr(
                                        cfg, 'kv_cache_dtype', 'auto'),
                                    page_size=getattr(
                                        cfg, 'kv_page_size', 0),
                                    n_pages=getattr(
                                        cfg, 'kv_n_pages', 0))


class MLP(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        dense = lambda features, names, name: nn.DenseGeneral(  # noqa: E731
            features, use_bias=False, name=name, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=_partitioned_init(nn.initializers.normal(0.02),
                                          names, cfg.partition_params))
        gate = maybe_lora(
            cfg, 'gate_proj', x,
            dense(cfg.ffn_dim, ('embed_fsdp', 'mlp'), 'gate_proj')(x),
            cfg.ffn_dim)
        up = maybe_lora(
            cfg, 'up_proj', x,
            dense(cfg.ffn_dim, ('embed_fsdp', 'mlp'), 'up_proj')(x),
            cfg.ffn_dim)
        # Gated-MLP activation: Llama uses SiLU; Gemma's GeGLU plugs in
        # through the config (duck-typed field, default silu).
        act = getattr(cfg, 'activation', 'silu')
        act_fn = (nn.silu if act == 'silu'
                  else lambda g: nn.gelu(g, approximate=True))
        hidden = act_fn(gate) * up
        return maybe_lora(
            cfg, 'down_proj', hidden,
            dense(cfg.dim, ('mlp', 'embed_fsdp'), 'down_proj')(hidden),
            cfg.dim)


class Block(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x: jax.Array, positions: jax.Array,
                 kv_mask: Optional[jax.Array] = None) -> jax.Array:
        cfg = self.config
        plus_one = getattr(cfg, 'norm_plus_one', False)
        x = x + Attention(cfg, name='attention')(
            RMSNorm(cfg.norm_eps, cfg.dtype, cfg.partition_params,
                    plus_one, name='attention_norm')(x),
            positions, kv_mask)
        x = x + MLP(cfg, name='mlp')(
            RMSNorm(cfg.norm_eps, cfg.dtype, cfg.partition_params,
                    plus_one, name='mlp_norm')(x))
        return x


def maybe_remat(cfg, block_base, *, scanned: bool):
    """Wrap a block class with the cfg's remat policy.  One definition
    for every family (incl. heterogeneous stacks like DeepSeek's dense
    prefix + scanned MoE suffix): the policy-name validation and the
    prevent_cse rule (only safe to disable inside a scan) must never
    diverge between call sites."""
    if not cfg.remat:
        return block_base
    policy_name = getattr(cfg, 'remat_policy', 'nothing')
    if policy_name == 'save_attn':
        policy = jax.checkpoint_policies.save_only_these_names(
            'attn_out', 'attn_lse')
    elif policy_name == 'nothing':
        policy = jax.checkpoint_policies.nothing_saveable
    else:
        raise ValueError(
            f'Unknown remat_policy {policy_name!r}; expected '
            "'nothing' or 'save_attn'.")
    return nn.remat(block_base, prevent_cse=not scanned, policy=policy)


def apply_blocks(cfg, block_base, x: jax.Array, positions: jax.Array,
                 kv_mask: Optional[jax.Array], *,
                 n_layers: Optional[int] = None,
                 sow_intermediates: bool = False,
                 block_kwargs: Optional[Dict[str, Any]] = None
                 ) -> jax.Array:
    """Run the layer stack with the cfg's remat/scan policy — shared by
    every decoder family (Llama/Gemma/GPT-2/Qwen, Mixtral and
    DeepSeek's MoE suffix via the keyword extensions) so the scan
    metadata, remat policy, and cache axes can never diverge between
    them.  Must be called from inside the parent's @nn.compact
    __call__.

    `n_layers` overrides cfg.n_layers (heterogeneous stacks scan only
    their homogeneous suffix); `sow_intermediates` adds the
    'intermediates' scan axis MoE families need for their sown router
    aux losses; `block_kwargs` is forwarded to every block
    construction."""
    block_cls = maybe_remat(cfg, block_base, scanned=cfg.scan_layers)
    length = cfg.n_layers if n_layers is None else n_layers
    kwargs = block_kwargs or {}
    if cfg.scan_layers:
        variable_axes = {'params': 0}
        if sow_intermediates:
            variable_axes['intermediates'] = 0
        if getattr(cfg, 'decode', False):
            variable_axes['cache'] = 0
        x, _ = nn.scan(
            lambda mod, carry, _: (mod(carry, positions, kv_mask),
                                   None),
            variable_axes=variable_axes,
            split_rngs={'params': True},
            length=length,
            metadata_params={nn.PARTITION_NAME: 'layers'},
        )(block_cls(cfg, name='layers', **kwargs), x, None)
    else:
        for i in range(length):
            x = block_cls(cfg, name=f'layer_{i}', **kwargs)(
                x, positions, kv_mask)
    return x


class Llama(nn.Module):
    """Decoder-only transformer; returns logits [B, S, vocab]."""
    config: LlamaConfig

    @nn.compact
    def __call__(self, tokens: jax.Array,
                 positions: Optional[jax.Array] = None,
                 kv_mask: Optional[jax.Array] = None,
                 return_hidden: bool = False) -> jax.Array:
        cfg = self.config
        if positions is None:
            positions = default_positions(tokens)
        embed = self.param(
            'tok_embed',
            _partitioned_init(nn.initializers.normal(1.0),
                              ('vocab', 'embed_fsdp'),
                              cfg.partition_params),
            (cfg.vocab_size, cfg.dim), cfg.param_dtype)
        x = embed_lookup(cfg, embed, tokens)
        x = apply_blocks(cfg, Block, x, positions, kv_mask)
        x = RMSNorm(cfg.norm_eps, cfg.dtype, cfg.partition_params,
                    name='final_norm')(x)
        # Tied-untied: separate output head (Llama3 unties embeddings).
        head = nn.DenseGeneral(
            cfg.vocab_size, use_bias=False, name='lm_head',
            dtype=jnp.float32, param_dtype=cfg.param_dtype,
            kernel_init=_partitioned_init(nn.initializers.normal(0.02),
                                          ('embed_fsdp', 'vocab'),
                                          cfg.partition_params))
        if return_hidden:
            # Chunked-loss path (train/trainer.py chunked CE): the
            # caller applies the head per sequence chunk so the full
            # [B, S, vocab] f32 logits never materialize.  The head
            # must still be CREATED here (1-token apply, discarded) so
            # the param tree is identical either way.
            _ = head(x[:, :1])
            return x
        return head(x)


def num_params(config: LlamaConfig) -> int:
    """Analytic parameter count."""
    cfg = config
    per_layer = (cfg.dim * cfg.head_dim * (cfg.n_heads + 2 * cfg.n_kv_heads)
                 + cfg.n_heads * cfg.head_dim * cfg.dim
                 + 3 * cfg.dim * cfg.ffn_dim + 2 * cfg.dim)
    return (cfg.vocab_size * cfg.dim * 2        # embed + head
            + cfg.n_layers * per_layer + cfg.dim)
