"""Gemma model family, TPU-first (reference parity: llm/gemma/ serves
Gemma via vLLM; here it is first-party like the Llama family).

Architectural deltas from Llama (models/llama.py), all config-driven so
the attention/MLP/block machinery is shared:
  - GeGLU MLP (gelu(gate) * up) via `activation='gelu'`
  - RMSNorm stores the weight as an offset from 1 (`norm_plus_one`)
  - embeddings scaled by sqrt(dim) at lookup
  - lm_head tied to the token embedding (logits = x @ embedᵀ)
  - head_dim decoupled from dim (e.g. 7B: dim=3072, 16 heads × 256)
  - optional final-logit softcapping (Gemma-2 convention)

Sharing the blocks means Gemma inherits the Pallas flash/ring attention
paths, GQA, KV-cache decode, scan + remat, and the logical-axis
sharding rules without re-implementation.  Gemma-7B is MQA-like
(n_kv_heads=1, 8 query heads): decode scores all heads against the
single cached kv head via the grouped epilogue's kvh==1 branch
(ops/grouped_attention.py) — the cache is never broadcast to n_heads
in HBM.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from skypilot_tpu.models import llama


@dataclasses.dataclass(frozen=True)
class GemmaConfig:
    """Duck-typed against LlamaConfig: the shared blocks read these
    fields plus `activation`/`norm_plus_one` via getattr."""
    name: str
    vocab_size: int = 256128
    dim: int = 3072
    n_layers: int = 28
    n_heads: int = 16
    n_kv_heads: int = 16
    head_dim: int = 256
    ffn_dim: int = 24576
    max_seq_len: int = 8192
    rope_theta: float = 10000.0
    norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = True
    remat: bool = True
    attention_impl: str = 'flash'
    decode: bool = False
    kv_cache_dtype: str = 'auto'     # 'auto' | 'int8' (llama.py)
    # Paged slot-mode KV cache (llama.py run_cached_attention):
    # 0 = contiguous rows.
    kv_page_size: int = 0
    kv_n_pages: int = 0
    partition_params: bool = True
    # Gemma-specific knobs consumed by the shared blocks / this module.
    activation: str = 'gelu'
    norm_plus_one: bool = True
    final_logit_softcap: Optional[float] = None   # Gemma-2: 30.0
    # LoRA (shared llama.maybe_lora machinery).
    lora_rank: int = 0
    lora_alpha: float = 16.0
    lora_targets: tuple = ('q_proj', 'k_proj', 'v_proj', 'o_proj')


CONFIGS: Dict[str, GemmaConfig] = {
    'gemma-tiny': GemmaConfig('gemma-tiny', vocab_size=512, dim=128,
                              n_layers=2, n_heads=2, n_kv_heads=1,
                              head_dim=64, ffn_dim=256, max_seq_len=512),
    'gemma-2b': GemmaConfig('gemma-2b', dim=2048, n_layers=18,
                            n_heads=8, n_kv_heads=1, head_dim=256,
                            ffn_dim=16384),
    'gemma-7b': GemmaConfig('gemma-7b'),
    # NOTE: no gemma2-* configs yet — real Gemma-2 additionally has
    # post-layernorms, attention-logit softcapping, and alternating
    # local/global attention; shipping a half-faithful config under
    # that name would silently diverge from published checkpoints.
    # The final_logit_softcap knob is available for experimentation.
}


def get_config(name: str, **overrides: Any) -> GemmaConfig:
    if name not in CONFIGS:
        raise ValueError(f'Unknown gemma config {name!r}; '
                         f'available: {sorted(CONFIGS)}')
    return dataclasses.replace(CONFIGS[name], **overrides)


class Gemma(nn.Module):
    """Decoder-only transformer; returns logits [B, S, vocab]."""
    config: GemmaConfig

    @nn.compact
    def __call__(self, tokens: jax.Array,
                 positions: Optional[jax.Array] = None,
                 kv_mask: Optional[jax.Array] = None,
                 return_hidden: bool = False) -> jax.Array:
        cfg = self.config
        if positions is None:
            positions = llama.default_positions(tokens)
        # Small init: the head is tied to this matrix, so (with the
        # sqrt(dim) lookup scaling compensating on the input side)
        # init-time logits stay O(sqrt(dim)*0.02), not O(sqrt(dim)).
        embed = self.param(
            'tok_embed',
            llama._partitioned_init(  # pylint: disable=protected-access
                nn.initializers.normal(0.02), ('vocab', 'embed_fsdp'),
                cfg.partition_params),
            (cfg.vocab_size, cfg.dim), cfg.param_dtype)
        x = llama.embed_lookup(cfg, embed, tokens)
        # Gemma scales embeddings by sqrt(dim) at lookup.
        x = (x.astype(jnp.float32) * (cfg.dim ** 0.5)).astype(cfg.dtype)

        x = llama.apply_blocks(cfg, llama.Block, x, positions, kv_mask)
        x = llama.RMSNorm(cfg.norm_eps, cfg.dtype, cfg.partition_params,
                          plus_one=True, name='final_norm')(x)
        if return_hidden:
            # Chunked-CE path (train/trainer.py): the head is tied —
            # no extra params to create.
            return x
        # Tied head: logits against the embedding matrix (no lm_head
        # params — Gemma ties embeddings; self.param returns the
        # unboxed array).
        logits = jnp.einsum('bsd,vd->bsv', x.astype(jnp.float32),
                            embed.astype(jnp.float32))
        if cfg.final_logit_softcap:
            cap = cfg.final_logit_softcap
            logits = cap * jnp.tanh(logits / cap)
        return logits


def num_params(config: GemmaConfig) -> int:
    """Analytic parameter count (tied head: embed counted once)."""
    cfg = config
    per_layer = (cfg.dim * cfg.head_dim * (cfg.n_heads
                                           + 2 * cfg.n_kv_heads)
                 + cfg.n_heads * cfg.head_dim * cfg.dim
                 + 3 * cfg.dim * cfg.ffn_dim + 2 * cfg.dim)
    return cfg.vocab_size * cfg.dim + cfg.n_layers * per_layer + cfg.dim
