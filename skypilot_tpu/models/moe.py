"""Mixture-of-Experts model family (Mixtral-style), expert-parallel.

The reference only *serves* MoE models through vLLM/DeepSpeed recipes
(reference `llm/mixtral/`, `llm/dbrx/` — SURVEY.md §2.11: "vLLM/DeepSpeed
handle EP internally"); here expert parallelism is first-party:

  - experts are stacked parameters [E, ...] carrying the `experts`
    logical axis, sharded over the `expert` mesh axis
    (parallel/sharding.py);
  - routing is top-k (k=2 for Mixtral) with a capacity factor; dispatch
    and combine are dense one-hot einsums (GShard/Switch formulation) so
    shapes stay static and XLA lowers the token movement to
    all-to-alls over the expert axis — no ragged ops, no host control
    flow;
  - a load-balance auxiliary loss (Switch Transformers) is sown under
    `intermediates/aux_loss` for the trainer to fold in;
  - everything else (GQA flash attention, RMSNorm, rope, scan/remat)
    reuses the Llama blocks, so dp/fsdp/tp compose with ep — including
    the grouped no-K/V-repeat decode epilogue: Mixtral's 4:1 GQA cache
    is read at n_kv_heads per step (ops/grouped_attention.py).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict

import flax.linen as nn
import jax
import jax.numpy as jnp

from skypilot_tpu.models import llama


@dataclasses.dataclass(frozen=True)
class MoEConfig(llama.LlamaConfig):
    n_experts: int = 8
    experts_per_token: int = 2
    # capacity per expert = capacity_factor * tokens * k / E.
    capacity_factor: float = 1.25
    router_aux_coef: float = 0.02
    # 'dense': GShard one-hot einsum dispatch — [T, E, C] dispatch/
    # combine tensors, O(k*T^2*D) FLOPs and O(k*T^2) memory in the
    # token count (fine at small scale, a real ceiling at 8x7B).
    # 'sparse': sort-by-expert + capacity scatter/segment-add — static
    # shapes (argsort + scatter, no ragged ops), identical routing
    # semantics (same choice-major intra-expert ordering, same
    # capacity drops), FLOPs linear in tokens and flat in E.
    moe_dispatch: str = 'dense'


CONFIGS: Dict[str, MoEConfig] = {
    'mixtral-tiny': MoEConfig(
        'mixtral-tiny', vocab_size=512, dim=256, n_layers=2, n_heads=2,
        n_kv_heads=1, ffn_dim=512, max_seq_len=512, n_experts=4,
        experts_per_token=2),
    'mixtral-8x7b': MoEConfig(
        'mixtral-8x7b', vocab_size=32000, dim=4096, n_layers=32,
        n_heads=32, n_kv_heads=8, ffn_dim=14336, max_seq_len=32768,
        rope_theta=1e6, n_experts=8, experts_per_token=2),
    'mixtral-8x22b': MoEConfig(
        'mixtral-8x22b', vocab_size=32768, dim=6144, n_layers=56,
        n_heads=48, n_kv_heads=8, ffn_dim=16384, max_seq_len=65536,
        rope_theta=1e6, n_experts=8, experts_per_token=2),
}


def get_config(name: str, **overrides: Any) -> MoEConfig:
    if name not in CONFIGS:
        raise ValueError(f'Unknown MoE config {name!r}; '
                         f'available: {sorted(CONFIGS)}')
    return dataclasses.replace(CONFIGS[name], **overrides)


class MoEMLP(nn.Module):
    """Top-k routed expert FFN with capacity-based dense dispatch."""
    config: MoEConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        if cfg.moe_dispatch not in ('dense', 'sparse'):
            # A typo must not silently run the O(T^2) dense path the
            # user was trying to avoid.
            raise ValueError(
                f"moe_dispatch must be 'dense' or 'sparse', got "
                f'{cfg.moe_dispatch!r}')
        b, s, d = x.shape
        n_exp, k = cfg.n_experts, cfg.experts_per_token
        tokens = b * s
        capacity = max(
            1, int(cfg.capacity_factor * tokens * k / n_exp))

        xf = x.reshape(tokens, d)
        # Router in f32 for a stable softmax.
        router_logits = nn.DenseGeneral(
            n_exp, use_bias=False, name='router', dtype=jnp.float32,
            param_dtype=cfg.param_dtype,
            kernel_init=llama._partitioned_init(  # pylint: disable=protected-access
                nn.initializers.normal(0.02), ('embed', None),
                cfg.partition_params))(xf.astype(jnp.float32))
        probs = jax.nn.softmax(router_logits, axis=-1)       # [T, E]
        gate_vals, expert_idx = jax.lax.top_k(probs, k)      # [T, k]
        # Mixtral renormalizes the top-k gate weights.
        gate_vals = gate_vals / jnp.sum(gate_vals, -1, keepdims=True)

        # Load-balance aux loss (Switch): mean gate fraction * mean
        # dispatch fraction per expert, scaled by E.
        assign = jax.nn.one_hot(expert_idx, n_exp,
                                dtype=jnp.int32)             # [T, k, E]
        me = jnp.mean(probs, axis=0)
        ce = jnp.mean(assign.sum(1).astype(jnp.float32), axis=0)
        aux = cfg.router_aux_coef * n_exp * jnp.sum(me * ce)
        self.sow('intermediates', 'aux_loss', aux)

        from skypilot_tpu.parallel import sharding as sharding_lib
        if cfg.moe_dispatch == 'sparse':
            # Sort-based dispatch: O(kT log kT + kT*D) instead of the
            # dense path's O(kT^2*D) einsums / [T, E, C] residency.
            # Choice-major flattening matches the dense path's
            # intra-expert ordering exactly, so capacity drops (and
            # therefore outputs) are identical.
            flat_e = expert_idx.T.reshape(k * tokens)        # [kT]
            flat_t = jnp.tile(jnp.arange(tokens), k)         # [kT]
            flat_g = gate_vals.T.reshape(k * tokens)         # [kT]
            order = jnp.argsort(flat_e, stable=True)
            sort_e = flat_e[order]
            sort_t = flat_t[order]
            sort_g = flat_g[order]
            # Position within the expert's buffer: index in the sorted
            # list minus the expert's first index.
            first = jnp.searchsorted(sort_e, sort_e, side='left')
            pos = jnp.arange(k * tokens) - first
            keep_s = pos < capacity
            # Scatter kept rows into the [E*C, D] expert buffers;
            # overflow rows get an out-of-range index and mode='drop'.
            flat_idx = jnp.where(keep_s, sort_e * capacity + pos,
                                 n_exp * capacity)
            expert_in = jnp.zeros((n_exp * capacity, d), xf.dtype)
            expert_in = expert_in.at[flat_idx].set(
                xf[sort_t], mode='drop').reshape(
                    n_exp, capacity, d)
        else:
            # Position of each (token, choice) in its expert's buffer:
            # running count of prior assignments to the same expert,
            # counted over the flattened (choice-major) assignment
            # list so the two choices of one token never collide.
            flat_assign = assign.transpose(1, 0, 2).reshape(
                k * tokens, n_exp)                           # [kT, E]
            pos_flat = jnp.cumsum(flat_assign, axis=0) - flat_assign
            position = jnp.einsum('fe,fe->f', pos_flat,
                                  flat_assign).reshape(k, tokens)
            position = position.T                             # [T, k]
            keep = position < capacity

            # Dense dispatch/combine tensors.
            pos_oh = jax.nn.one_hot(
                jnp.where(keep, position, capacity),
                capacity, dtype=xf.dtype)                    # [T, k, C]
            disp = jnp.einsum('tke,tkc->tec',
                              assign.astype(xf.dtype), pos_oh)
            comb = jnp.einsum('tec,tk,tke->tec', disp,
                              gate_vals.astype(xf.dtype),
                              assign.astype(xf.dtype))       # weighted
            expert_in = jnp.einsum('tec,td->ecd', disp, xf)  # [E, C, D]
        # Pin the expert-parallel layout: XLA turns the dispatch
        # (einsum or scatter) into an all-to-all over the expert axis.
        expert_in = sharding_lib.maybe_constraint(
            expert_in, jax.sharding.PartitionSpec('expert', None, None))

        # Batched expert FFN over the expert-stacked params.
        gate_p = self.param(
            'gate_proj',
            llama._partitioned_init(  # pylint: disable=protected-access
                nn.initializers.normal(0.02),
                ('experts', 'embed_fsdp', 'mlp'), cfg.partition_params),
            (n_exp, d, cfg.ffn_dim), cfg.param_dtype)
        up_p = self.param(
            'up_proj',
            llama._partitioned_init(  # pylint: disable=protected-access
                nn.initializers.normal(0.02),
                ('experts', 'embed_fsdp', 'mlp'), cfg.partition_params),
            (n_exp, d, cfg.ffn_dim), cfg.param_dtype)
        down_p = self.param(
            'down_proj',
            llama._partitioned_init(  # pylint: disable=protected-access
                nn.initializers.normal(0.02),
                ('experts', 'mlp', 'embed_fsdp'), cfg.partition_params),
            (n_exp, cfg.ffn_dim, d), cfg.param_dtype)

        h = expert_in.astype(cfg.dtype)
        gate = jnp.einsum('ecd,edf->ecf', h, gate_p.astype(cfg.dtype))
        up = jnp.einsum('ecd,edf->ecf', h, up_p.astype(cfg.dtype))
        act = nn.silu(gate) * up
        expert_out = jnp.einsum('ecf,efd->ecd', act,
                                down_p.astype(cfg.dtype))    # [E, C, D]

        if cfg.moe_dispatch == 'sparse':
            # Combine: gather each kept assignment's expert output and
            # segment-add it back onto its token, gate-weighted.
            flat_out = expert_out.reshape(n_exp * capacity, d)
            gathered = flat_out.at[flat_idx].get(
                mode='fill', fill_value=0)                   # [kT, D]
            weighted = gathered * (sort_g *
                                   keep_s)[:, None].astype(cfg.dtype)
            out = jnp.zeros((tokens, d), cfg.dtype).at[sort_t].add(
                weighted)
        else:
            out = jnp.einsum('tec,ecd->td', comb.astype(cfg.dtype),
                             expert_out)
        return out.reshape(b, s, d)


class MoEBlock(nn.Module):
    config: MoEConfig

    @nn.compact
    def __call__(self, x: jax.Array, positions: jax.Array,
                 kv_mask=None) -> jax.Array:
        cfg = self.config
        x = x + llama.Attention(cfg, name='attention')(
            llama.RMSNorm(cfg.norm_eps, cfg.dtype, cfg.partition_params,
                          name='attention_norm')(x), positions, kv_mask)
        x = x + MoEMLP(cfg, name='moe_mlp')(
            llama.RMSNorm(cfg.norm_eps, cfg.dtype, cfg.partition_params,
                          name='mlp_norm')(x))
        return x


class Mixtral(nn.Module):
    """Decoder-only MoE transformer; returns logits [B, S, vocab]."""
    config: MoEConfig

    @nn.compact
    def __call__(self, tokens: jax.Array, positions=None,
                 kv_mask=None, return_hidden: bool = False) -> jax.Array:
        cfg = self.config
        if positions is None:
            positions = llama.default_positions(tokens)
        embed = self.param(
            'tok_embed',
            llama._partitioned_init(  # pylint: disable=protected-access
                nn.initializers.normal(1.0), ('vocab', 'embed_fsdp'),
                cfg.partition_params),
            (cfg.vocab_size, cfg.dim), cfg.param_dtype)
        x = jnp.take(embed.astype(cfg.dtype), tokens, axis=0)

        # Shared stack recipe (scan metadata + remat policy live in ONE
        # place; sow axis for the router aux loss).
        x = llama.apply_blocks(cfg, MoEBlock, x, positions, kv_mask,
                               sow_intermediates=True)
        x = llama.RMSNorm(cfg.norm_eps, cfg.dtype, cfg.partition_params,
                          name='final_norm')(x)
        head = nn.DenseGeneral(
            cfg.vocab_size, use_bias=False, name='lm_head',
            dtype=jnp.float32, param_dtype=cfg.param_dtype,
            kernel_init=llama._partitioned_init(  # pylint: disable=protected-access
                nn.initializers.normal(0.02), ('embed_fsdp', 'vocab'),
                cfg.partition_params))
        if return_hidden:
            # Chunked-CE path; see models/llama.py — the head params
            # must exist either way.
            _ = head(x[:, :1])
            return x
        return head(x)


def num_params(config: MoEConfig) -> int:
    cfg = config
    attn = cfg.dim * cfg.head_dim * (cfg.n_heads + 2 * cfg.n_kv_heads) \
        + cfg.n_heads * cfg.head_dim * cfg.dim
    moe = cfg.n_experts * 3 * cfg.dim * cfg.ffn_dim \
        + cfg.dim * cfg.n_experts
    per_layer = attn + moe + 2 * cfg.dim
    return (cfg.vocab_size * cfg.dim * 2
            + cfg.n_layers * per_layer + cfg.dim)
