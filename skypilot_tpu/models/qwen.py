"""Qwen2 model family, TPU-first (reference parity: the reference's
flagship serving recipes are Qwen via vLLM — llm/qwen/serve-110b.yaml,
llm/qwen/; here the family is first-party like Llama/Gemma).

Architectural deltas from Llama (models/llama.py), all config-driven
so the attention/MLP/block machinery is shared:
  - biases on the Q/K/V projections (`attention_bias=True`; O stays
    bias-free) — the Qwen2 signature;
  - small models (0.5B/1.5B) tie the lm_head to the token embedding,
    larger ones untie (`tie_embeddings`);
  - rope_theta 1e6 and 32k context by default.

Sharing the blocks means Qwen inherits the Pallas flash/ring attention
paths, GQA, slot-mode KV-cache decode (continuous batching), scan +
remat, LoRA, and the logical-axis sharding rules without
re-implementation.  Decode is bandwidth-optimal: the KV cache lives
and is *read* at n_kv_heads — the head-group broadcast happens inside
the grouped einsum (ops/grouped_attention.py), never in HBM, so e.g.
qwen2-72b's 8:1 GQA reads 8x fewer cache bytes per step than a
repeat-based epilogue would.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from skypilot_tpu.models import llama


@dataclasses.dataclass(frozen=True)
class QwenConfig:
    """Duck-typed against LlamaConfig; the shared blocks additionally
    read `attention_bias` via getattr."""
    name: str
    vocab_size: int = 152064
    dim: int = 3584
    n_layers: int = 28
    n_heads: int = 28
    n_kv_heads: int = 4
    head_dim: int = 128
    ffn_dim: int = 18944
    max_seq_len: int = 32768
    rope_theta: float = 1e6
    norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = True
    remat: bool = True
    remat_policy: str = 'nothing'
    attention_impl: str = 'flash'
    decode: bool = False
    kv_cache_dtype: str = 'auto'     # 'auto' | 'int8' (llama.py)
    # Paged slot-mode KV cache (llama.py run_cached_attention):
    # 0 = contiguous rows.
    kv_page_size: int = 0
    kv_n_pages: int = 0
    partition_params: bool = True
    attention_bias: bool = True      # the Qwen2 signature
    tie_embeddings: bool = False
    # LoRA (shared llama.maybe_lora machinery).
    lora_rank: int = 0
    lora_alpha: float = 16.0
    lora_targets: tuple = ('q_proj', 'k_proj', 'v_proj', 'o_proj')


CONFIGS: Dict[str, QwenConfig] = {
    'qwen-tiny': QwenConfig('qwen-tiny', vocab_size=512, dim=128,
                            n_layers=2, n_heads=4, n_kv_heads=2,
                            head_dim=32, ffn_dim=256, max_seq_len=512,
                            tie_embeddings=True),
    'qwen2-0.5b': QwenConfig('qwen2-0.5b', vocab_size=151936, dim=896,
                             n_layers=24, n_heads=14, n_kv_heads=2,
                             head_dim=64, ffn_dim=4864,
                             tie_embeddings=True),
    'qwen2-7b': QwenConfig('qwen2-7b'),
    'qwen2-72b': QwenConfig('qwen2-72b', dim=8192, n_layers=80,
                            n_heads=64, n_kv_heads=8, head_dim=128,
                            ffn_dim=29568),
}


def get_config(name: str, **overrides: Any) -> QwenConfig:
    if name not in CONFIGS:
        raise ValueError(f'Unknown qwen config {name!r}; '
                         f'available: {sorted(CONFIGS)}')
    return dataclasses.replace(CONFIGS[name], **overrides)


class Qwen(nn.Module):
    """Decoder-only transformer; returns logits [B, S, vocab]."""
    config: QwenConfig

    @nn.compact
    def __call__(self, tokens: jax.Array,
                 positions: Optional[jax.Array] = None,
                 kv_mask: Optional[jax.Array] = None,
                 return_hidden: bool = False) -> jax.Array:
        cfg = self.config
        if positions is None:
            positions = llama.default_positions(tokens)
        embed = self.param(
            'tok_embed',
            llama._partitioned_init(  # pylint: disable=protected-access
                nn.initializers.normal(0.02), ('vocab', 'embed_fsdp'),
                cfg.partition_params),
            (cfg.vocab_size, cfg.dim), cfg.param_dtype)
        x = llama.embed_lookup(cfg, embed, tokens)
        x = llama.apply_blocks(cfg, llama.Block, x, positions, kv_mask)
        x = llama.RMSNorm(cfg.norm_eps, cfg.dtype, cfg.partition_params,
                          name='final_norm')(x)
        if cfg.tie_embeddings:
            if return_hidden:
                return x  # tied head, no params to create
            return jnp.einsum('bsd,vd->bsv', x.astype(jnp.float32),
                              embed.astype(jnp.float32))
        head = nn.DenseGeneral(
            cfg.vocab_size, use_bias=False, name='lm_head',
            dtype=jnp.float32, param_dtype=cfg.param_dtype,
            kernel_init=llama._partitioned_init(  # pylint: disable=protected-access
                nn.initializers.normal(0.02), ('embed_fsdp', 'vocab'),
                cfg.partition_params))
        if return_hidden:
            _ = head(x[:, :1])  # create params; see models/llama.py
            return x
        return head(x)


def num_params(config: QwenConfig) -> int:
    """Analytic parameter count (QKV biases included)."""
    cfg = config
    qkv_out = cfg.head_dim * (cfg.n_heads + 2 * cfg.n_kv_heads)
    per_layer = (cfg.dim * qkv_out + qkv_out            # qkv + biases
                 + cfg.n_heads * cfg.head_dim * cfg.dim  # o_proj
                 + 3 * cfg.dim * cfg.ffn_dim             # gated mlp
                 + 2 * cfg.dim)                          # 2 norms
    total = cfg.vocab_size * cfg.dim + cfg.n_layers * per_layer + cfg.dim
    if not cfg.tie_embeddings:
        total += cfg.dim * cfg.vocab_size
    return total
