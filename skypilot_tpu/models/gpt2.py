"""GPT-2 model family, TPU-first (reference parity: llm/gpt-2/ runs
Karpathy's llm.c build via SkyPilot; here the model is first-party).

A second *architecture* family, not a Llama retune: LayerNorm with
bias, learned positional embeddings (no rope), biased projections,
single-head-group MHA, GELU MLP, tied lm_head.  Attention still runs on
the shared Pallas flash kernel and params carry the same logical axis
names, so fsdp/tensor sharding rules apply unchanged.  Cached decode
goes through llama.run_cached_attention with n_kv_heads == n_heads,
which the grouped epilogue (ops/grouped_attention.py) dispatches to its
plain per-head MHA branch — same code path as the GQA families, no
grouping overhead.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from skypilot_tpu.models import llama
from skypilot_tpu.ops import flash_attention as fa


@dataclasses.dataclass(frozen=True)
class Gpt2Config:
    name: str
    vocab_size: int = 50257
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    ffn_dim: int = 3072
    max_seq_len: int = 1024
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = True
    remat: bool = True
    attention_impl: str = 'flash'
    # Serving mode: KV cache via the shared llama.run_cached_attention.
    decode: bool = False
    kv_cache_dtype: str = 'auto'     # 'auto' | 'int8' (llama.py)
    # Paged slot-mode KV cache (llama.py run_cached_attention):
    # 0 = contiguous rows.
    kv_page_size: int = 0
    kv_n_pages: int = 0
    partition_params: bool = True

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


CONFIGS: Dict[str, Gpt2Config] = {
    'gpt2-tiny': Gpt2Config('gpt2-tiny', vocab_size=512, dim=128,
                            n_layers=2, n_heads=2, ffn_dim=256,
                            max_seq_len=256),
    'gpt2': Gpt2Config('gpt2'),
    'gpt2-medium': Gpt2Config('gpt2-medium', dim=1024, n_layers=24,
                              n_heads=16, ffn_dim=4096),
    'gpt2-large': Gpt2Config('gpt2-large', dim=1280, n_layers=36,
                             n_heads=20, ffn_dim=5120),
    'gpt2-xl': Gpt2Config('gpt2-xl', dim=1600, n_layers=48, n_heads=25,
                          ffn_dim=6400),
}


def get_config(name: str, **overrides: Any) -> Gpt2Config:
    if name not in CONFIGS:
        raise ValueError(f'Unknown gpt2 config {name!r}; '
                         f'available: {sorted(CONFIGS)}')
    return dataclasses.replace(CONFIGS[name], **overrides)


def _pinit(init, names, partition):
    return llama._partitioned_init(init, names, partition)  # pylint: disable=protected-access


class LayerNorm(nn.Module):
    eps: float
    dtype: Any
    partition: bool = True

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        d = x.shape[-1]
        scale = self.param('scale',
                           _pinit(nn.initializers.ones, ('embed',),
                                  self.partition), (d,), jnp.float32)
        bias = self.param('bias',
                          _pinit(nn.initializers.zeros, ('embed',),
                                 self.partition), (d,), jnp.float32)
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.mean((xf - mean) ** 2, axis=-1, keepdims=True)
        out = (xf - mean) * jax.lax.rsqrt(var + self.eps)
        return (out * scale + bias).astype(self.dtype)


class Gpt2Attention(nn.Module):
    config: Gpt2Config

    @nn.compact
    def __call__(self, x: jax.Array,
                 kv_mask: Optional[jax.Array] = None) -> jax.Array:
        cfg = self.config
        b, s, _ = x.shape
        h, hd = cfg.n_heads, cfg.head_dim
        dense = lambda features, names, name, init_std: nn.DenseGeneral(  # noqa: E731
            features, axis=-1, use_bias=True, name=name,
            dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=_pinit(nn.initializers.normal(init_std), names,
                               cfg.partition_params))
        qkv = dense((3, h, hd), ('embed_fsdp', None, 'heads', 'head_dim'),
                    'qkv_proj', 0.02)(x)
        q, k, v = (jnp.transpose(qkv[:, :, i], (0, 2, 1, 3))
                   for i in range(3))
        if cfg.decode:
            # run_cached_attention returns [B, S, H, hd] already.
            out = llama.run_cached_attention(
                self, q, k, v, kv_mask, n_kv_heads=h,
                max_seq_len=cfg.max_seq_len,
                dtype=cfg.dtype,
                kv_cache_dtype=getattr(cfg, 'kv_cache_dtype', 'auto'),
                page_size=getattr(cfg, 'kv_page_size', 0),
                n_pages=getattr(cfg, 'kv_n_pages', 0),
                ).reshape(b, s, h * hd)
        else:
            out = (fa.flash_attention(q, k, v)
                   if cfg.attention_impl == 'flash'
                   else fa.mha_reference(q, k, v))
            out = jnp.transpose(out, (0, 2, 1, 3)).reshape(b, s, h * hd)
        # GPT-2 scales residual-writing projections by 1/sqrt(2L).
        return dense(cfg.dim, ('heads', 'embed_fsdp'), 'o_proj',
                     0.02 / (2 * cfg.n_layers) ** 0.5)(out)


class Gpt2Mlp(nn.Module):
    config: Gpt2Config

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        up = nn.DenseGeneral(
            cfg.ffn_dim, use_bias=True, name='up_proj', dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=_pinit(nn.initializers.normal(0.02),
                               ('embed_fsdp', 'mlp'),
                               cfg.partition_params))(x)
        hidden = nn.gelu(up, approximate=True)
        return nn.DenseGeneral(
            cfg.dim, use_bias=True, name='down_proj', dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=_pinit(
                nn.initializers.normal(0.02 / (2 * cfg.n_layers) ** 0.5),
                ('mlp', 'embed_fsdp'), cfg.partition_params))(hidden)


class Gpt2Block(nn.Module):
    config: Gpt2Config

    @nn.compact
    def __call__(self, x: jax.Array,
                 positions: Optional[jax.Array] = None,
                 kv_mask: Optional[jax.Array] = None) -> jax.Array:
        # positions accepted for the shared apply_blocks signature;
        # GPT-2 adds absolute positions at the embedding instead.
        del positions
        cfg = self.config
        x = x + Gpt2Attention(cfg, name='attention')(
            LayerNorm(cfg.norm_eps, cfg.dtype, cfg.partition_params,
                      name='ln_1')(x), kv_mask)
        x = x + Gpt2Mlp(cfg, name='mlp')(
            LayerNorm(cfg.norm_eps, cfg.dtype, cfg.partition_params,
                      name='ln_2')(x))
        return x


class Gpt2(nn.Module):
    """Decoder-only transformer; returns logits [B, S, vocab]."""
    config: Gpt2Config

    @nn.compact
    def __call__(self, tokens: jax.Array,
                 positions: Optional[jax.Array] = None,
                 kv_mask: Optional[jax.Array] = None,
                 return_hidden: bool = False) -> jax.Array:
        cfg = self.config
        if positions is None:
            positions = llama.default_positions(tokens)
        embed = self.param(
            'tok_embed',
            _pinit(nn.initializers.normal(0.02), ('vocab', 'embed_fsdp'),
                   cfg.partition_params),
            (cfg.vocab_size, cfg.dim), cfg.param_dtype)
        pos_embed = self.param(
            'pos_embed',
            _pinit(nn.initializers.normal(0.01), (None, 'embed_fsdp'),
                   cfg.partition_params),
            (cfg.max_seq_len, cfg.dim), cfg.param_dtype)
        x = (jnp.take(embed, tokens, axis=0)
             + jnp.take(pos_embed, positions, axis=0)).astype(cfg.dtype)

        x = llama.apply_blocks(cfg, Gpt2Block, x, positions, kv_mask)
        x = LayerNorm(cfg.norm_eps, cfg.dtype, cfg.partition_params,
                      name='ln_f')(x)
        if return_hidden:
            return x  # chunked-CE path; tied head, no params to make
        # Tied lm_head (GPT-2 ties input/output embeddings).
        logits = jnp.einsum('bsd,vd->bsv', x.astype(jnp.float32),
                            embed.astype(jnp.float32))
        return logits


def num_params(config: Gpt2Config) -> int:
    cfg = config
    per_layer = (4 * cfg.dim * cfg.dim + 3 * cfg.dim + cfg.dim   # attn
                 + 2 * cfg.dim * cfg.ffn_dim + cfg.ffn_dim + cfg.dim
                 + 4 * cfg.dim)                                  # 2 LN
    return (cfg.vocab_size * cfg.dim + cfg.max_seq_len * cfg.dim
            + cfg.n_layers * per_layer + 2 * cfg.dim)
