"""Model registry: name -> (flax module, config).

Families: llama-* / llama3* (models/llama.py), mixtral-* MoE
(models/moe.py), gemma-* (models/gemma.py), gpt2-* (models/gpt2.py),
qwen* (models/qwen.py), deepseek-* MLA+MoE (models/deepseek.py).
The trainer and serving engine resolve models through `get_model` so
new families plug in without touching the training loop.
"""
from __future__ import annotations

from typing import Any, Tuple


def get_model(name: str, **overrides: Any) -> Tuple[Any, Any]:
    """Return (nn.Module instance, config) for a model name."""
    from skypilot_tpu.models import (deepseek, gemma, gpt2, llama, moe,
                                     qwen)
    if name in deepseek.CONFIGS:
        config = deepseek.get_config(name, **overrides)
        return deepseek.DeepSeek(config), config
    if name in moe.CONFIGS:
        config = moe.get_config(name, **overrides)
        return moe.Mixtral(config), config
    if name in llama.CONFIGS:
        config = llama.get_config(name, **overrides)
        return llama.Llama(config), config
    if name in gemma.CONFIGS:
        config = gemma.get_config(name, **overrides)
        return gemma.Gemma(config), config
    if name in gpt2.CONFIGS:
        config = gpt2.get_config(name, **overrides)
        return gpt2.Gpt2(config), config
    if name in qwen.CONFIGS:
        config = qwen.get_config(name, **overrides)
        return qwen.Qwen(config), config
    raise ValueError(f'Unknown model {name!r}; '
                     f'available: {available_models()}')


def num_params(config: Any) -> int:
    """Analytic parameter count, dispatched by config family —
    families duck-type each other's fields, so calling one family's
    counter on another's config returns a silently-wrong number."""
    from skypilot_tpu.models import (deepseek, gemma, gpt2, llama, moe,
                                     qwen)
    for mod, cfg_cls in ((deepseek, deepseek.DeepSeekConfig),
                         (moe, moe.MoEConfig),
                         (gemma, gemma.GemmaConfig),
                         (gpt2, gpt2.Gpt2Config),
                         (qwen, qwen.QwenConfig)):
        if isinstance(config, cfg_cls):
            return mod.num_params(config)
    return llama.num_params(config)


def active_params(config: Any) -> int:
    """Parameters that a forward pass actually multiplies per token.

    Equal to num_params for dense families; MoE families only route
    each token through `experts_per_token` of the `n_experts` expert
    FFNs, so the inactive experts' weights are subtracted (DeepSeek's
    shared experts and first-k dense layers always run and stay
    counted).  Pure host-side arithmetic — no JAX, no device work."""
    from skypilot_tpu.models import deepseek, moe
    total = num_params(config)
    if isinstance(config, deepseek.DeepSeekConfig):
        moe_layers = max(0, config.n_layers - config.first_k_dense)
        inactive = max(0, config.n_experts - config.experts_per_token)
        # Router-gated experts are 3 matrices (gate/up/down) of
        # [dim, moe_ffn_dim] each.
        return total - moe_layers * inactive * 3 * config.dim \
            * config.moe_ffn_dim
    if isinstance(config, moe.MoEConfig):
        inactive = max(0, config.n_experts - config.experts_per_token)
        return total - config.n_layers * inactive * 3 * config.dim \
            * config.ffn_dim
    return total


def flops_per_token_parts(config: Any) -> Tuple[float, float]:
    """(base, attn_per_ctx): the analytic FORWARD cost of one decoded
    token is ``base + attn_per_ctx * context``.

    base is the context-free 2·active-params matmul cost (2 FLOPs per
    MAC); attn_per_ctx prices the seq-dependent QK^T and PV matmuls —
    2 FLOPs per MAC over n_heads query heads at the family's qk/v
    head widths per live context position.  The serving ledger
    (observability/ledger.py) composes these with per-step context
    sums; bench.py's train-side twin (_attn_flops_per_token) applies
    the same shape with the 6x fwd+bwd rule instead."""
    from skypilot_tpu.models import deepseek
    base = 2.0 * active_params(config)
    if isinstance(config, deepseek.DeepSeekConfig):
        # MLA: scores at qk_head_dim (nope+rope), values at v_head_dim.
        width = config.qk_head_dim + config.v_head_dim
    else:
        head_dim = getattr(config, 'head_dim',
                           config.dim // config.n_heads)
        width = 2 * head_dim
    attn_per_ctx = 2.0 * config.n_layers * config.n_heads * width
    return base, attn_per_ctx


def flops_per_token(config: Any, context: int) -> float:
    """Analytic forward FLOPs to decode one token whose attention
    spans `context` live positions."""
    base, attn = flops_per_token_parts(config)
    return base + attn * context


def available_models():
    from skypilot_tpu.models import (deepseek, gemma, gpt2, llama, moe,
                                     qwen)
    return (sorted(llama.CONFIGS) + sorted(moe.CONFIGS)
            + sorted(gemma.CONFIGS) + sorted(gpt2.CONFIGS)
            + sorted(qwen.CONFIGS) + sorted(deepseek.CONFIGS))
