"""Model registry: name -> (flax module, config).

Families: llama-* / llama3* (models/llama.py), mixtral-* MoE
(models/moe.py), gemma-* (models/gemma.py), gpt2-* (models/gpt2.py),
qwen* (models/qwen.py), deepseek-* MLA+MoE (models/deepseek.py).
The trainer and serving engine resolve models through `get_model` so
new families plug in without touching the training loop.
"""
from __future__ import annotations

from typing import Any, Tuple


def get_model(name: str, **overrides: Any) -> Tuple[Any, Any]:
    """Return (nn.Module instance, config) for a model name."""
    from skypilot_tpu.models import (deepseek, gemma, gpt2, llama, moe,
                                     qwen)
    if name in deepseek.CONFIGS:
        config = deepseek.get_config(name, **overrides)
        return deepseek.DeepSeek(config), config
    if name in moe.CONFIGS:
        config = moe.get_config(name, **overrides)
        return moe.Mixtral(config), config
    if name in llama.CONFIGS:
        config = llama.get_config(name, **overrides)
        return llama.Llama(config), config
    if name in gemma.CONFIGS:
        config = gemma.get_config(name, **overrides)
        return gemma.Gemma(config), config
    if name in gpt2.CONFIGS:
        config = gpt2.get_config(name, **overrides)
        return gpt2.Gpt2(config), config
    if name in qwen.CONFIGS:
        config = qwen.get_config(name, **overrides)
        return qwen.Qwen(config), config
    raise ValueError(f'Unknown model {name!r}; '
                     f'available: {available_models()}')


def num_params(config: Any) -> int:
    """Analytic parameter count, dispatched by config family —
    families duck-type each other's fields, so calling one family's
    counter on another's config returns a silently-wrong number."""
    from skypilot_tpu.models import (deepseek, gemma, gpt2, llama, moe,
                                     qwen)
    for mod, cfg_cls in ((deepseek, deepseek.DeepSeekConfig),
                         (moe, moe.MoEConfig),
                         (gemma, gemma.GemmaConfig),
                         (gpt2, gpt2.Gpt2Config),
                         (qwen, qwen.QwenConfig)):
        if isinstance(config, cfg_cls):
            return mod.num_params(config)
    return llama.num_params(config)


def available_models():
    from skypilot_tpu.models import (deepseek, gemma, gpt2, llama, moe,
                                     qwen)
    return (sorted(llama.CONFIGS) + sorted(moe.CONFIGS)
            + sorted(gemma.CONFIGS) + sorted(gpt2.CONFIGS)
            + sorted(qwen.CONFIGS) + sorted(deepseek.CONFIGS))
