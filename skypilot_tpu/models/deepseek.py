"""DeepSeek model family (V2/V3/R1): multi-head latent attention +
fine-grained MoE, TPU-first.

Reference parity: the reference serves DeepSeek-R1 through vLLM
(`llm/deepseek-r1/README.md`, `llm/deepseek-r1/deepseek-r1-671B.yaml`)
and Janus (`llm/deepseek-janus/`); it ships no model code.  Here the
family is first-party so MLA's latent KV cache — the whole point of
the architecture — is exploited on TPU:

  - **MLA (multi-head latent attention)**: K/V are up-projected from a
    shared low-rank latent `c = W_dkv x` (kv_lora_rank wide) plus a
    single shared RoPE key head.  Training materializes K/V and runs
    the Pallas flash kernel.  Decode uses the *absorbed* form —
    `q·k = (q_nope W_uk)·c + q_rope·k_rope` — so the KV cache holds
    only `kv_lora_rank + qk_rope_head_dim` floats per token (576 for
    V3 vs 32,768 for an equivalent MHA: ~57x less HBM per token).
    Structurally that is ordinary cached attention with ONE kv head of
    width `kv_lora_rank + qk_rope_head_dim`, so the decode path reuses
    llama.run_cached_attention unchanged — slot-mode continuous
    batching, kv read buckets, and the serving engine all work for
    free.
  - **DeepSeekMoE**: `first_k_dense` dense layers, then MoE layers =
    shared expert(s) + top-k routed experts (models/moe.py MoEMLP with
    the expert width swapped to `moe_ffn_dim`).  The dense prefix runs
    unscanned; the homogeneous MoE suffix is scanned (compile time
    O(1) in depth, same recipe as llama.apply_blocks).
  - RoPE applies only to the decoupled `qk_rope_head_dim` slice; the
    nope slice is position-independent (what makes the absorption
    legal).  The rotation itself is the framework-shared llama rope
    (bit-compat with upstream checkpoints is out of scope).

Training attention pads q/k/v to a lane-aligned head width for the
flash kernel (zero-padding is exact for dot products; the softmax
scale is pinned to the true `qk_head_dim`).

Known divergences from upstream DeepSeek v3/r1 — this family is
architecture-shaped, NOT checkpoint-compatible:

  - **Router**: routed experts use the shared Mixtral-style
    softmax-top-k router with the Switch load-balancing aux loss
    (models/moe.py).  Real v3/r1 routes with per-expert *sigmoid*
    affinities, normalizes over the selected top-k only, and balances
    loss-free via a learned per-expert bias nudged by an online update
    — no aux-loss gradient interference.  Expect different expert
    utilization dynamics, and do not expect upstream router weights to
    transfer.
  - **RoPE**: plain `rope_theta=1e4` at the configured 32k context.
    Real v3/r1 trains at 4k native and extends to 128k with YaRN
    (scaled theta + attention-temperature correction).  Long-context
    behavior past a few thousand tokens therefore matches neither
    upstream quality nor its positional geometry.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
from jax.ad_checkpoint import checkpoint_name
import jax.numpy as jnp

from skypilot_tpu.models import llama
from skypilot_tpu.models import moe as moe_lib
from skypilot_tpu.ops import flash_attention as fa


@dataclasses.dataclass(frozen=True)
class DeepSeekConfig:
    """Duck-typed against LlamaConfig/MoEConfig where blocks are
    shared (MoEMLP reads ffn_dim/n_experts/...; apply-side helpers
    read dtype/partition_params/...)."""
    name: str
    vocab_size: int = 129280
    dim: int = 7168
    n_layers: int = 61
    n_heads: int = 128
    # MLA geometry (DeepSeek-V3 defaults).
    q_lora_rank: int = 1536          # 0 = full q projection (V2-Lite)
    kv_lora_rank: int = 512
    qk_nope_head_dim: int = 128
    qk_rope_head_dim: int = 64
    v_head_dim: int = 128
    # Dense MLP width (first_k_dense layers) / MoE geometry.
    ffn_dim: int = 18432
    first_k_dense: int = 3
    n_experts: int = 256             # routed experts
    experts_per_token: int = 8
    n_shared_experts: int = 1
    moe_ffn_dim: int = 2048          # per-expert (and per-shared) width
    capacity_factor: float = 1.25
    router_aux_coef: float = 0.001
    moe_dispatch: str = 'sparse'
    max_seq_len: int = 8192
    rope_theta: float = 10000.0
    norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = True
    remat: bool = True
    remat_policy: str = 'nothing'
    attention_impl: str = 'flash'    # flash | reference
    decode: bool = False
    # The absorbed latent cache (kvh==1) PARTICIPATES in int8 KV
    # quantization: one absmax scale per (latent, position) row of the
    # [B, 1, S, rkv+dr] cache — same layout as the GQA families.
    kv_cache_dtype: str = 'auto'     # 'auto' | 'int8' (llama.py)
    # Paged slot-mode KV cache (llama.py run_cached_attention):
    # 0 = contiguous rows.
    kv_page_size: int = 0
    kv_n_pages: int = 0
    partition_params: bool = True
    # Unused by MLA but read via getattr by shared helpers.
    sliding_window: Optional[int] = None
    lora_rank: int = 0

    @property
    def qk_head_dim(self) -> int:
        return self.qk_nope_head_dim + self.qk_rope_head_dim

    @property
    def latent_dim(self) -> int:
        """Per-token KV-cache width (the MLA headline number)."""
        return self.kv_lora_rank + self.qk_rope_head_dim


CONFIGS: Dict[str, DeepSeekConfig] = {
    # Structurally complete tiny config: q-LoRA on, 1 dense + MoE
    # suffix, shared expert — everything a test needs to exercise.
    'deepseek-tiny': DeepSeekConfig(
        'deepseek-tiny', vocab_size=512, dim=64, n_layers=2, n_heads=4,
        q_lora_rank=24, kv_lora_rank=32, qk_nope_head_dim=16,
        qk_rope_head_dim=8, v_head_dim=16, ffn_dim=128,
        first_k_dense=1, n_experts=4, experts_per_token=2,
        n_shared_experts=1, moe_ffn_dim=64, max_seq_len=256,
        scan_layers=False, remat=False),
    'deepseek-v2-lite': DeepSeekConfig(
        'deepseek-v2-lite', vocab_size=102400, dim=2048, n_layers=27,
        n_heads=16, q_lora_rank=0, kv_lora_rank=512,
        qk_nope_head_dim=128, qk_rope_head_dim=64, v_head_dim=128,
        ffn_dim=10944, first_k_dense=1, n_experts=64,
        experts_per_token=6, n_shared_experts=2, moe_ffn_dim=1408,
        max_seq_len=32768),
    'deepseek-v2': DeepSeekConfig(
        'deepseek-v2', vocab_size=102400, dim=5120, n_layers=60,
        n_heads=128, q_lora_rank=1536, kv_lora_rank=512,
        qk_nope_head_dim=128, qk_rope_head_dim=64, v_head_dim=128,
        ffn_dim=12288, first_k_dense=1, n_experts=160,
        experts_per_token=6, n_shared_experts=2, moe_ffn_dim=1536,
        max_seq_len=32768),
    'deepseek-v3': DeepSeekConfig('deepseek-v3', max_seq_len=32768),
    # R1 is V3's architecture post-trained for reasoning (the
    # reference's llm/deepseek-r1 recipe serves exactly this shape).
    'deepseek-r1': DeepSeekConfig('deepseek-r1', max_seq_len=32768),
}


def get_config(name: str, **overrides: Any) -> DeepSeekConfig:
    if name not in CONFIGS:
        raise ValueError(f'Unknown deepseek config {name!r}; '
                         f'available: {sorted(CONFIGS)}')
    return dataclasses.replace(CONFIGS[name], **overrides)


def _round_up(x: int, mult: int) -> int:
    return (x + mult - 1) // mult * mult


class MLAAttention(nn.Module):
    """Multi-head latent attention (training + absorbed decode)."""
    config: DeepSeekConfig

    @nn.compact
    def __call__(self, x: jax.Array, positions: jax.Array,
                 kv_mask: Optional[jax.Array] = None) -> jax.Array:
        cfg = self.config
        b, s, _ = x.shape
        h = cfg.n_heads
        dn, dr, dv = (cfg.qk_nope_head_dim, cfg.qk_rope_head_dim,
                      cfg.v_head_dim)
        rkv = cfg.kv_lora_rank

        def dense(features, names, name):
            return nn.DenseGeneral(
                features, axis=-1, use_bias=False, name=name,
                dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                kernel_init=llama._partitioned_init(  # pylint: disable=protected-access
                    nn.initializers.normal(0.02), names,
                    cfg.partition_params))

        # --- queries: optional low-rank bottleneck (V3) or full (Lite).
        if cfg.q_lora_rank:
            cq = dense(cfg.q_lora_rank, ('embed_fsdp', 'q_lora'),
                       'q_down')(x)
            cq = llama.RMSNorm(cfg.norm_eps, cfg.dtype,
                               cfg.partition_params, name='q_norm')(cq)
            q = dense((h, dn + dr), ('q_lora', 'heads', 'head_dim'),
                      'q_up')(cq)
        else:
            q = dense((h, dn + dr), ('embed_fsdp', 'heads', 'head_dim'),
                      'q_proj')(x)
        q = jnp.transpose(q, (0, 2, 1, 3))        # [B, H, S, dn+dr]
        q_nope, q_rope = q[..., :dn], q[..., dn:]
        q_rope = llama.apply_rope(q_rope, positions, cfg.rope_theta)

        # --- latent KV + decoupled shared rope key.
        c = dense(rkv, ('embed_fsdp', 'kv_lora'), 'kv_down')(x)
        c = llama.RMSNorm(cfg.norm_eps, cfg.dtype, cfg.partition_params,
                          name='kv_norm')(c)      # [B, S, rkv]
        # The decoupled rope key is ONE shared head — it cannot shard
        # over 'tensor' (size 1); every tensor shard keeps a copy.
        k_rope = dense((1, dr), ('embed_fsdp', None, 'head_dim'),
                       'k_rope_proj')(x)          # [B, S, 1, dr]
        k_rope = jnp.transpose(k_rope, (0, 2, 1, 3))
        k_rope = llama.apply_rope(k_rope, positions, cfg.rope_theta)

        # Up-projections as raw params: the SAME weights serve the
        # training path (materialize K/V) and the decode path
        # (absorbed into q / out) — einsum layouts differ, a
        # DenseGeneral can't express both.
        wuk = self.param(
            'kv_up_k',
            llama._partitioned_init(  # pylint: disable=protected-access
                nn.initializers.normal(0.02),
                ('kv_lora', 'heads', 'head_dim'), cfg.partition_params),
            (rkv, h, dn), cfg.param_dtype)
        wuv = self.param(
            'kv_up_v',
            llama._partitioned_init(  # pylint: disable=protected-access
                nn.initializers.normal(0.02),
                ('kv_lora', 'heads', 'head_dim'), cfg.partition_params),
            (rkv, h, dv), cfg.param_dtype)
        wuk_c = wuk.astype(cfg.dtype)
        wuv_c = wuv.astype(cfg.dtype)
        scale = cfg.qk_head_dim ** -0.5

        if cfg.decode:
            out = self._absorbed_cached(q_nope, q_rope, c, k_rope,
                                        wuk_c, wuv_c, kv_mask, scale)
        else:
            out = self._train_attention(q_nope, q_rope, c, k_rope,
                                        wuk_c, wuv_c, scale)
        out = checkpoint_name(out, 'attn_out')    # [B, S, H, dv]
        flat = out.reshape(b, s, h * dv)
        return nn.DenseGeneral(
            cfg.dim, use_bias=False, name='o_proj', dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=llama._partitioned_init(  # pylint: disable=protected-access
                nn.initializers.normal(0.02 / (2 * cfg.n_layers) ** 0.5),
                ('heads', 'embed_fsdp'), cfg.partition_params))(flat)

    def _train_attention(self, q_nope, q_rope, c, k_rope, wuk, wuv,
                         scale) -> jax.Array:
        """Materialized K/V + flash kernel (or reference math)."""
        cfg = self.config
        dn, dr, dv = (cfg.qk_nope_head_dim, cfg.qk_rope_head_dim,
                      cfg.v_head_dim)
        h = cfg.n_heads
        b, _, s, _ = q_nope.shape
        k_nope = jnp.einsum('bsr,rhn->bhsn', c, wuk)
        v = jnp.einsum('bsr,rhv->bhsv', c, wuv)
        k_rope_b = jnp.broadcast_to(k_rope, (b, h, s, dr))
        q = jnp.concatenate([q_nope, q_rope], axis=-1)
        k = jnp.concatenate([k_nope, k_rope_b], axis=-1)
        if cfg.attention_impl == 'flash':
            # Lane-align the head width for the Pallas kernel; zero
            # padding is exact (adds 0 to every dot product) and the
            # explicit scale ignores the padded width.
            dq = _round_up(max(dn + dr, dv), 128)
            pad_qk = dq - (dn + dr)
            spec = [(0, 0), (0, 0), (0, 0), (0, pad_qk)]
            out = fa.flash_attention(
                jnp.pad(q, spec), jnp.pad(k, spec),
                jnp.pad(v, [(0, 0), (0, 0), (0, 0), (0, dq - dv)]),
                scale, True, fa.DEFAULT_BLOCK_Q, fa.DEFAULT_BLOCK_KV,
                None)[..., :dv]
        else:
            out = fa.mha_reference(q, k, v, scale=scale)
        return jnp.transpose(out, (0, 2, 1, 3))   # [B, S, H, dv]

    def _absorbed_cached(self, q_nope, q_rope, c, k_rope, wuk, wuv,
                         kv_mask, scale) -> jax.Array:
        """Decode: cache [c ; k_rope] as ONE latent kv head.

        q_eff = [q_nope·W_uk ; q_rope]   (width rkv + dr)
        k_eff = [c ; k_rope]             (the cache entry)
        v_eff = c zero-padded to width rkv + dr
        then  q_eff·k_eff == q·k  and  (probs·v_eff)[..:rkv]·W_uv == out,
        so llama.run_cached_attention (slot-mode continuous batching,
        kv buckets) is reused verbatim.  Its grouped epilogue's kvh==1
        branch scores all H query heads directly against the single
        [B, 1, S, rkv+dr] latent (ops/grouped_attention.py) — the cache
        is never broadcast to H heads, preserving MLA's bandwidth win
        at decode.  Its internal scale is width**-0.5 of the LATENT
        width; q is pre-multiplied to land on the true qk_head_dim
        scale."""
        cfg = self.config
        rkv, dr = cfg.kv_lora_rank, cfg.qk_rope_head_dim
        b, h, s, _ = q_nope.shape
        q_abs = jnp.einsum('bhsn,rhn->bhsr', q_nope, wuk)
        q_eff = jnp.concatenate([q_abs, q_rope], axis=-1)
        width = rkv + dr
        q_eff = q_eff * (scale / (width ** -0.5))
        k_eff = jnp.concatenate(
            [c[:, None], k_rope], axis=-1)        # [B, 1, S, rkv+dr]
        v_eff = jnp.pad(c[:, None], [(0, 0), (0, 0), (0, 0), (0, dr)])
        out_latent = llama.run_cached_attention(
            self, q_eff, k_eff, v_eff, kv_mask, n_kv_heads=1,
            max_seq_len=cfg.max_seq_len, dtype=cfg.dtype,
            kv_cache_dtype=getattr(cfg, 'kv_cache_dtype', 'auto'),
            page_size=getattr(cfg, 'kv_page_size', 0),
            n_pages=getattr(cfg, 'kv_n_pages', 0))
        out_latent = out_latent[..., :rkv]        # [B, S, H, rkv]
        return jnp.einsum('bshr,rhv->bshv', out_latent, wuv)


class SharedExpertMLP(nn.Module):
    """Always-on expert(s): a dense gated MLP of width
    n_shared_experts * moe_ffn_dim, added to the routed output."""
    config: DeepSeekConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        width = cfg.n_shared_experts * cfg.moe_ffn_dim
        shared_cfg = dataclasses.replace(cfg, ffn_dim=width)
        return llama.MLP(shared_cfg, name='shared_mlp')(x)


class DeepSeekBlock(nn.Module):
    config: DeepSeekConfig
    use_moe: bool = True

    @nn.compact
    def __call__(self, x: jax.Array, positions: jax.Array,
                 kv_mask: Optional[jax.Array] = None) -> jax.Array:
        cfg = self.config
        x = x + MLAAttention(cfg, name='attention')(
            llama.RMSNorm(cfg.norm_eps, cfg.dtype, cfg.partition_params,
                          name='attention_norm')(x), positions, kv_mask)
        h = llama.RMSNorm(cfg.norm_eps, cfg.dtype, cfg.partition_params,
                          name='mlp_norm')(x)
        if self.use_moe:
            routed_cfg = dataclasses.replace(cfg,
                                             ffn_dim=cfg.moe_ffn_dim)
            y = moe_lib.MoEMLP(routed_cfg, name='moe_mlp')(h)
            y = y + SharedExpertMLP(cfg, name='shared')(h)
        else:
            y = llama.MLP(cfg, name='mlp')(h)
        return x + y


class DeepSeek(nn.Module):
    """Decoder-only MLA+MoE transformer; returns logits [B, S, V]."""
    config: DeepSeekConfig

    @nn.compact
    def __call__(self, tokens: jax.Array, positions=None, kv_mask=None,
                 return_hidden: bool = False) -> jax.Array:
        cfg = self.config
        if positions is None:
            positions = llama.default_positions(tokens)
        embed = self.param(
            'tok_embed',
            llama._partitioned_init(  # pylint: disable=protected-access
                nn.initializers.normal(1.0), ('vocab', 'embed_fsdp'),
                cfg.partition_params),
            (cfg.vocab_size, cfg.dim), cfg.param_dtype)
        x = llama.embed_lookup(cfg, embed, tokens)

        # Dense prefix (first_k_dense layers), unscanned — it is
        # heterogeneous with the MoE suffix, and 1-3 layers don't move
        # compile time.
        n_dense = min(cfg.first_k_dense, cfg.n_layers)
        # The unscanned prefix must keep prevent_cse=True; only the
        # scanned suffix may drop it (llama.maybe_remat owns the rule).
        prefix_cls = llama.maybe_remat(cfg, DeepSeekBlock,
                                       scanned=False)
        for i in range(n_dense):
            x = prefix_cls(cfg, use_moe=False, name=f'dense_{i}')(
                x, positions, kv_mask)

        # Homogeneous MoE suffix: scanned (llama.apply_blocks recipe).
        n_moe = cfg.n_layers - n_dense
        if n_moe:
            x = llama.apply_blocks(cfg, DeepSeekBlock, x, positions,
                                   kv_mask, n_layers=n_moe,
                                   sow_intermediates=True,
                                   block_kwargs={'use_moe': True})

        x = llama.RMSNorm(cfg.norm_eps, cfg.dtype, cfg.partition_params,
                          name='final_norm')(x)
        head = nn.DenseGeneral(
            cfg.vocab_size, use_bias=False, name='lm_head',
            dtype=jnp.float32, param_dtype=cfg.param_dtype,
            kernel_init=llama._partitioned_init(  # pylint: disable=protected-access
                nn.initializers.normal(0.02), ('embed_fsdp', 'vocab'),
                cfg.partition_params))
        if return_hidden:
            # Chunked-CE path; head params must exist either way (see
            # models/llama.py).
            _ = head(x[:, :1])
            return x
        return head(x)


def num_params(config: DeepSeekConfig) -> int:
    """Analytic parameter count (norm scales included)."""
    cfg = config
    h = cfg.n_heads
    dn, dr, dv = (cfg.qk_nope_head_dim, cfg.qk_rope_head_dim,
                  cfg.v_head_dim)
    if cfg.q_lora_rank:
        q = cfg.dim * cfg.q_lora_rank + cfg.q_lora_rank \
            + cfg.q_lora_rank * h * (dn + dr)
    else:
        q = cfg.dim * h * (dn + dr)
    attn = (q + cfg.dim * cfg.kv_lora_rank + cfg.kv_lora_rank  # down+norm
            + cfg.dim * dr                                     # k_rope
            + cfg.kv_lora_rank * h * (dn + dv)                 # up k+v
            + h * dv * cfg.dim)                                # o_proj
    dense_mlp = 3 * cfg.dim * cfg.ffn_dim
    moe_mlp = (cfg.n_experts * 3 * cfg.dim * cfg.moe_ffn_dim
               + cfg.dim * cfg.n_experts                       # router
               + 3 * cfg.dim * cfg.n_shared_experts * cfg.moe_ffn_dim)
    n_dense = min(cfg.first_k_dense, cfg.n_layers)
    per_layer_common = attn + 2 * cfg.dim
    total = (cfg.vocab_size * cfg.dim * 2 + cfg.dim
             + n_dense * (per_layer_common + dense_mlp)
             + (cfg.n_layers - n_dense) * (per_layer_common + moe_mlp))
    return total
