from skypilot_tpu.cli import main

if __name__ == '__main__':
    main()
