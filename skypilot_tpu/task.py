"""The Task model: a unit of work with resource requirements.

Counterpart of the reference's sky/task.py:171-1221.  A Task carries:
name, setup, run (bash string or a Python callable taking
(node_rank, host_ips)), workdir, num_nodes (logical nodes — for TPU slices
each node is a whole slice and fan-out to hosts is handled by the backend),
envs with ${VAR} substitution, file_mounts, storage_mounts, a set of
candidate Resources, and an optional serve `service` spec.  YAML round-trip
via `from_yaml_config` / `to_yaml_config`.
"""
from __future__ import annotations

import os
import re
from typing import Any, Callable, Dict, List, Optional, Set, Union

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import sky_logging
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import schemas

logger = sky_logging.init_logger(__name__)

_VALID_NAME_REGEX = '[a-zA-Z0-9]+(?:[._-]{1,2}[a-zA-Z0-9]+)*'
_VALID_ENV_VAR_REGEX = '[a-zA-Z_][a-zA-Z0-9_]*'

RunFn = Callable[[int, List[str]], Optional[str]]


def _fill_in_env_vars(yaml_field: Any, task_envs: Dict[str, str]) -> Any:
    """Substitute ${VAR} / $VAR occurrences using task envs (reference
    sky/task.py:73 _fill_in_env_vars)."""
    if isinstance(yaml_field, str):
        def repl(m: 're.Match[str]') -> str:
            var = m.group(1) or m.group(2)
            return task_envs.get(var, m.group(0))

        return re.sub(r'\$\{(' + _VALID_ENV_VAR_REGEX + r')\}|'
                      r'\$(' + _VALID_ENV_VAR_REGEX + r')\b', repl,
                      yaml_field)
    if isinstance(yaml_field, dict):
        return {k: _fill_in_env_vars(v, task_envs)
                for k, v in yaml_field.items()}
    if isinstance(yaml_field, list):
        return [_fill_in_env_vars(v, task_envs) for v in yaml_field]
    return yaml_field


class Task:
    """A coarse-grained unit of work submitted to the framework."""

    def __init__(
        self,
        name: Optional[str] = None,
        *,
        setup: Optional[str] = None,
        run: Optional[Union[str, RunFn]] = None,
        envs: Optional[Dict[str, str]] = None,
        workdir: Optional[str] = None,
        num_nodes: Optional[int] = None,
        file_mounts: Optional[Dict[str, str]] = None,
    ) -> None:
        self.name = name
        self.setup = setup
        self.run = run
        self.workdir = workdir
        self._envs = {k: str(v) if v is not None else ''
                      for k, v in (envs or {}).items()}
        self._num_nodes = 1
        if num_nodes is not None:
            self.num_nodes = num_nodes
        self.file_mounts: Optional[Dict[str, str]] = None
        if file_mounts is not None:
            self.set_file_mounts(file_mounts)
        self.storage_mounts: Dict[str, Any] = {}
        self.service: Optional[Any] = None  # serve.SkyServiceSpec
        self.resources: Union[Set[resources_lib.Resources],
                              List[resources_lib.Resources]] = {
                                  resources_lib.Resources()}
        self.best_resources: Optional[resources_lib.Resources] = None
        self.estimated_outputs_size_gb: Optional[float] = None
        # Registered into the ambient DAG context, if any (sky/task.py).
        dag = dag_lib.get_current_dag()
        if dag is not None:
            dag.add(self)

    # -- validation --------------------------------------------------------
    def validate(self) -> None:
        self.validate_name()
        self.validate_run()
        if self.workdir is not None:
            full = os.path.abspath(os.path.expanduser(self.workdir))
            if not os.path.isdir(full):
                raise exceptions.TaskValidationError(
                    f'Workdir must be an existing directory: {self.workdir}')

    def validate_name(self) -> None:
        if self.name is not None and not re.fullmatch(_VALID_NAME_REGEX,
                                                      self.name):
            raise exceptions.TaskValidationError(
                f'Invalid task name {self.name!r}: must match '
                f'{_VALID_NAME_REGEX}')

    def validate_run(self) -> None:
        if self.run is None or isinstance(self.run, str):
            return
        if callable(self.run):
            # Python-callable run fn receives (node_rank, host_ips) and
            # returns the bash command for that rank (reference
            # sky/task.py:269 run-as-generator form).
            return
        raise exceptions.TaskValidationError(
            f'run must be a string, callable, or None; got {type(self.run)}')

    # -- envs --------------------------------------------------------------
    @property
    def envs(self) -> Dict[str, str]:
        return dict(self._envs)

    def update_envs(
            self, envs: Union[None, List[tuple], Dict[str, str]]) -> 'Task':
        if envs is None:
            return self
        if isinstance(envs, (list, tuple)):
            envs = dict(envs)
        for key, value in envs.items():
            if not isinstance(key, str) or not re.fullmatch(
                    _VALID_ENV_VAR_REGEX, key):
                raise exceptions.TaskValidationError(
                    f'Invalid env var name {key!r}.')
            self._envs[key] = str(value) if value is not None else ''
        return self

    # -- num_nodes ---------------------------------------------------------
    @property
    def num_nodes(self) -> int:
        return self._num_nodes

    @num_nodes.setter
    def num_nodes(self, num_nodes: Optional[int]) -> None:
        if num_nodes is None:
            num_nodes = 1
        if not isinstance(num_nodes, int) or num_nodes < 1:
            raise exceptions.TaskValidationError(
                f'num_nodes must be a positive int, got {num_nodes!r}')
        self._num_nodes = num_nodes

    # -- resources ---------------------------------------------------------
    def set_resources(
        self, resources: Union[resources_lib.Resources,
                               Set[resources_lib.Resources],
                               List[resources_lib.Resources]]
    ) -> 'Task':
        if isinstance(resources, resources_lib.Resources):
            resources = {resources}
        if not resources:
            raise exceptions.TaskValidationError('Empty resources set.')
        self.resources = resources
        return self

    @property
    def resources_ordered(self) -> bool:
        """Whether candidate resources are a preference-ordered list."""
        return isinstance(self.resources, list)

    def get_preferred_resources(self) -> List[resources_lib.Resources]:
        if isinstance(self.resources, list):
            return list(self.resources)
        return sorted(self.resources, key=repr)

    # -- file mounts -------------------------------------------------------
    def set_file_mounts(
            self, file_mounts: Optional[Dict[str, str]]) -> 'Task':
        if file_mounts is None:
            self.file_mounts = None
            return self
        for target, source in file_mounts.items():
            if target.endswith('/') or source.endswith('/'):
                raise exceptions.TaskValidationError(
                    'File mount paths cannot end with a slash; got '
                    f'{target}: {source}. For directories, omit the '
                    'trailing slash.')
            if not _is_cloud_store_url(source):
                full = os.path.abspath(os.path.expanduser(source))
                if not os.path.exists(full):
                    raise exceptions.TaskValidationError(
                        f'File mount source {source!r} does not exist '
                        'locally.')
        self.file_mounts = dict(file_mounts)
        return self

    def update_file_mounts(self, file_mounts: Dict[str, str]) -> 'Task':
        merged = dict(self.file_mounts or {})
        merged.update(file_mounts)
        return self.set_file_mounts(merged)

    def set_storage_mounts(self, storage_mounts: Optional[Dict[str, Any]]
                           ) -> 'Task':
        self.storage_mounts = dict(storage_mounts or {})
        return self

    # -- service -----------------------------------------------------------
    def set_service(self, service: Optional[Any]) -> 'Task':
        self.service = service
        return self

    # -- YAML round-trip ---------------------------------------------------
    @staticmethod
    def from_yaml_config(config: Dict[str, Any],
                         env_overrides: Optional[List[tuple]] = None
                         ) -> 'Task':
        if env_overrides is not None:
            new_envs = dict(config.get('envs') or {})
            new_envs.update(dict(env_overrides))
            config['envs'] = new_envs
        for key in list(config.get('envs', {}) or {}):
            value = config['envs'][key]
            if value is None:
                raise exceptions.TaskValidationError(
                    f'Env var {key!r} has no value set. Set it in the YAML '
                    'or with --env.')
            config['envs'][key] = str(value)
        # Env substitution happens before schema validation so that
        # `${VAR}` placeholders in any field are resolved first
        # (reference sky/task.py:347 from_yaml_config).
        config = _fill_in_env_vars(config, config.get('envs', {}) or {})
        schemas.validate(config, schemas.get_task_schema(),
                         exceptions.TaskValidationError, 'Invalid task: ')

        task = Task(
            config.get('name'),
            setup=config.get('setup'),
            run=config.get('run'),
            workdir=config.get('workdir'),
            num_nodes=config.get('num_nodes'),
            envs=config.get('envs'),
        )
        if config.get('file_mounts') is not None:
            # Separate plain-path mounts from inline storage-spec mounts.
            plain: Dict[str, str] = {}
            storages: Dict[str, Any] = {}
            for target, source in config['file_mounts'].items():
                if isinstance(source, str):
                    plain[target] = source
                elif isinstance(source, dict):
                    storages[target] = source
            if plain:
                task.set_file_mounts(plain)
            if storages:
                from skypilot_tpu.data import storage as storage_lib
                task.set_storage_mounts({
                    t: storage_lib.Storage.from_yaml_config(s)
                    for t, s in storages.items()
                })
        resources_config = config.get('resources')
        task.set_resources(
            resources_lib.Resources.from_yaml_config(resources_config))
        if config.get('service') is not None:
            from skypilot_tpu.serve import service_spec
            task.set_service(
                service_spec.SkyServiceSpec.from_yaml_config(
                    config['service']))
        outputs = config.get('outputs')
        if isinstance(outputs, dict):
            size = outputs.get('estimated_size_gigabytes')
            if size is not None:
                task.estimated_outputs_size_gb = float(size)
        task.validate()
        return task

    @staticmethod
    def from_yaml(yaml_path: str) -> 'Task':
        config = common_utils.read_yaml(yaml_path)
        if isinstance(config, str):
            raise exceptions.TaskValidationError(
                f'{yaml_path} is not a YAML mapping.')
        return Task.from_yaml_config(config or {})

    def to_yaml_config(self) -> Dict[str, Any]:
        config: Dict[str, Any] = {}

        def add(key: str, value: Any) -> None:
            if value is not None and value != {} and value != []:
                config[key] = value

        add('name', self.name)
        if len(self.get_preferred_resources()) == 1:
            add('resources',
                self.get_preferred_resources()[0].to_yaml_config())
        elif self.resources_ordered:
            add('resources', {
                'ordered': [r.to_yaml_config()
                            for r in self.get_preferred_resources()]
            })
        else:
            add('resources', {
                'any_of': [r.to_yaml_config()
                           for r in self.get_preferred_resources()]
            })
        if self.service is not None:
            add('service', self.service.to_yaml_config())
        if self._num_nodes != 1:
            add('num_nodes', self._num_nodes)
        add('envs', self._envs or None)
        if self.estimated_outputs_size_gb is not None:
            add('outputs', {
                'estimated_size_gigabytes': self.estimated_outputs_size_gb})
        add('workdir', self.workdir)
        add('setup', self.setup)
        add('run', self.run if isinstance(self.run, str) else None)
        add('file_mounts', self.file_mounts)
        if self.storage_mounts:
            add('storage_mounts_config', {
                t: s.to_yaml_config() for t, s in self.storage_mounts.items()
            })
        return config

    # -- DAG sugar ---------------------------------------------------------
    def __rshift__(self, other: 'Task') -> 'Task':
        dag = dag_lib.get_current_dag()
        if dag is None:
            raise exceptions.DagError(
                'Task >> Task requires an active `with Dag():` context.')
        dag.add_edge(self, other)
        return other

    def __repr__(self) -> str:
        if self.name:
            return f'Task({self.name})'
        s = 'Task(run='
        if isinstance(self.run, str):
            s += repr(common_utils.truncate_long_string(self.run, 20))
        else:
            s += repr(self.run)
        return s + ')'


def _is_cloud_store_url(url: str) -> bool:
    return bool(re.match(r'^(s3|gs|gcs|r2|az|cos|https?)://', url))
