"""Credential checking: which clouds are usable (reference: sky/check.py).

`check()` probes every registered cloud's `check_credentials`, persists the
enabled set to the state DB, and reports.  The optimizer consults the
cached enabled set; an empty cache triggers a refresh.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

from skypilot_tpu import clouds as clouds_lib
from skypilot_tpu import config as config_lib
from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)


def check(quiet: bool = False,
          cloud_names: Optional[Iterable[str]] = None) -> List[str]:
    """Probe credentials; persist + return the enabled cloud names."""
    allowed = config_lib.get_nested(('allowed_clouds',), None)
    results: Dict[str, Tuple[bool, Optional[str]]] = {}
    for name, cloud in sorted(clouds_lib.CLOUD_REGISTRY.items()):
        if cloud_names and name not in cloud_names:
            continue
        if allowed is not None and name not in [a.lower() for a in allowed]:
            results[name] = (False, 'disabled by allowed_clouds config')
            continue
        try:
            ok, reason = cloud.check_credentials()
        except Exception as e:  # pylint: disable=broad-except
            ok, reason = False, str(e)
        results[name] = (ok, reason)
    enabled = [name for name, (ok, _) in results.items() if ok]
    if cloud_names:
        # Partial check: merge with previously enabled clouds.
        prev = set(global_user_state.get_cached_enabled_clouds())
        prev -= {n for n, (ok, _) in results.items() if not ok}
        enabled = sorted(prev | set(enabled))
    global_user_state.set_enabled_clouds(enabled)
    if not quiet:
        for name, (ok, reason) in results.items():
            mark = '\x1b[92m✔\x1b[0m' if ok else '\x1b[91m✗\x1b[0m'
            line = f'  {mark} {name}'
            if not ok and reason:
                line += f': {reason}'
            logger.info(line)
        if not enabled:
            logger.info('No cloud is enabled.')
    return enabled


def get_cached_enabled_clouds_or_refresh(
        raise_if_no_cloud_access: bool = False) -> List[clouds_lib.Cloud]:
    names = global_user_state.get_cached_enabled_clouds()
    if not names:
        names = check(quiet=True)
    enabled = [clouds_lib.CLOUD_REGISTRY[n] for n in names
               if n in clouds_lib.CLOUD_REGISTRY]
    if raise_if_no_cloud_access and not enabled:
        raise exceptions.NoCloudAccessError(
            'No cloud access. Run `skytpu check` after configuring '
            'credentials.')
    return enabled
