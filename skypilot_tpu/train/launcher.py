"""Distributed bring-up: connect this process to the job's jax.distributed
rendezvous using the env contract injected by the gang driver
(agent/constants.py) — the TPU-native replacement for the reference's
torchrun/NCCL rendezvous over SKYPILOT_NODE_* env vars
(examples/nccl_test.yaml:31-41, SURVEY.md §2.12).
"""
from __future__ import annotations

import os
from typing import Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.agent import constants

logger = sky_logging.init_logger(__name__)


def maybe_initialize_distributed() -> bool:
    """Initialize jax.distributed from SKYTPU_* env vars if present.

    Returns True if a multi-process rendezvous was set up.  Single-process
    (one host, or env absent) is a no-op — jax works standalone.
    """
    coordinator = os.environ.get(constants.ENV_COORDINATOR_ADDR)
    num_processes = int(os.environ.get(constants.ENV_NUM_PROCESSES, '1'))
    process_id = int(os.environ.get(constants.ENV_PROCESS_ID, '0'))
    if coordinator is None or num_processes <= 1:
        return False
    import jax
    logger.info(f'jax.distributed.initialize(coordinator={coordinator}, '
                f'num_processes={num_processes}, process_id={process_id})')
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def process_info() -> dict:
    return {
        'process_id': int(os.environ.get(constants.ENV_PROCESS_ID, '0')),
        'num_processes': int(os.environ.get(constants.ENV_NUM_PROCESSES,
                                            '1')),
        'coordinator': os.environ.get(constants.ENV_COORDINATOR_ADDR),
        'accelerator': os.environ.get(constants.ENV_ACCELERATOR),
        'slice_id': os.environ.get(constants.ENV_MEGASCALE_SLICE_ID),
    }
