"""The sharded training loop: init → jit train_step → metrics.

MaxText-grade mechanics (SURVEY.md §7 hard part #6) without the framework
sprawl:
  - abstract init (jax.eval_shape) → per-param NamedShardings from the
    model's logical axis annotations → jit'd initializer with
    out_shardings, so the full model never materializes unsharded;
  - one jit'd train_step over the mesh: bf16 forward/backward (params
    kept f32), next-token CE with masking, global-norm clip, AdamW +
    cosine schedule, donated state (in-place buffers);
  - gradient accumulation by lax.scan over microbatches;
  - remat policy comes from the model config (nothing_saveable on blocks
    — recompute attention/MLP in backward, the HBM-for-FLOPs trade).
"""
from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any, Dict, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct
from flax.core import FrozenDict
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from skypilot_tpu import sky_logging
from skypilot_tpu.models import llama
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.parallel import sharding as sharding_lib

logger = sky_logging.init_logger(__name__)


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    model: str = 'llama-tiny'
    global_batch_size: int = 8
    seq_len: int = 512
    learning_rate: float = 3e-4
    warmup_steps: int = 100
    total_steps: int = 1000
    weight_decay: float = 0.1
    grad_clip_norm: float = 1.0
    grad_accum_steps: int = 1
    # Pipeline parallelism: microbatches per step when the mesh has a
    # pipe axis > 1 (None -> 2 * pipe stages, keeping the GPipe bubble
    # under a third).
    pipeline_microbatches: Optional[int] = None
    # Circular (interleaved) schedule: each stage holds this many
    # non-contiguous layer groups; bubble shrinks by the same factor
    # (parallel/pipeline.py gpipe circular_repeats).
    pipeline_circular_repeats: int = 1
    mesh: mesh_lib.MeshConfig = mesh_lib.MeshConfig()
    model_overrides: Dict[str, Any] = dataclasses.field(
        default_factory=dict)
    # Freeze everything except params whose path contains this
    # substring (e.g. 'lora' for adapter-only finetuning — reference
    # llm/llama-3_1-finetuning/lora.yaml semantics).  None = train all.
    train_only: Optional[str] = None
    # Persistent XLA compilation cache: a repeat/recovered run of the
    # same program skips the (20-40s on TPU) first-step compile.
    # Point it at the bucket-mounted checkpoint dir and preempted
    # managed jobs recover straight into a cached executable.
    compilation_cache_dir: Optional[str] = None
    # Chunked cross-entropy: apply the lm_head per `loss_chunk` tokens
    # of sequence (scan + remat) so the [B, S, vocab] f32 logits never
    # materialize — at long seq x large vocab they are the biggest
    # buffer in the step.  0 = off.  Requires llama/mixtral families.
    loss_chunk: int = 0
    seed: int = 0


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: Any
    apply_fn: Any = struct.field(pytree_node=False)
    tx: Any = struct.field(pytree_node=False)


def _trainable_mask(params: Any, needle: str) -> Any:
    """True exactly for params whose path contains `needle`."""
    import flax
    flat = flax.traverse_util.flatten_dict(params)
    mask = {k: any(needle in str(part) for part in k) for k in flat}
    return flax.traverse_util.unflatten_dict(mask)


def make_optimizer(config: TrainConfig) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=config.learning_rate,
        warmup_steps=config.warmup_steps,
        decay_steps=max(config.total_steps, config.warmup_steps + 1),
        end_value=config.learning_rate * 0.1)
    tx = optax.chain(
        optax.clip_by_global_norm(config.grad_clip_norm),
        optax.adamw(schedule, b1=0.9, b2=0.95, eps=1e-8,
                    weight_decay=config.weight_decay),
    )
    if config.train_only:
        # Frozen params get zero updates (optax.masked alone would let
        # raw gradients pass through for masked-out leaves).
        def labels(params):
            import flax
            mask = _trainable_mask(params, config.train_only)
            return flax.traverse_util.unflatten_dict({
                k: ('train' if v else 'freeze')
                for k, v in flax.traverse_util.flatten_dict(mask).items()
            })
        tx = optax.multi_transform(
            {'train': tx, 'freeze': optax.set_to_zero()}, labels)
    return tx


def sum_aux_losses(mutated_collections) -> jax.Array:
    """Total of every `aux_loss` sown during apply (MoE router
    load-balance terms; stacked over scanned layers)."""
    total = jnp.zeros((), jnp.float32)
    if not mutated_collections:
        return total
    flat = jax.tree_util.tree_flatten_with_path(
        dict(mutated_collections))[0]
    for path, leaf in flat:
        if any(getattr(p, 'key', '') == 'aux_loss' for p in path):
            total = total + jnp.sum(leaf)
    return total


def loss_fn(params, apply_fn, batch) -> Tuple[jax.Array, Dict[str, Any]]:
    logits, aux_loss = apply_fn({'params': params}, batch['inputs'])
    targets = batch['targets']
    mask = batch['mask']
    logits = logits.astype(jnp.float32)
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    total_weight = jnp.maximum(mask.sum(), 1.0)
    ce_loss = (ce * mask).sum() / total_weight
    loss = ce_loss + aux_loss
    accuracy = ((jnp.argmax(logits, -1) == targets) * mask).sum() / \
        total_weight
    return loss, {'loss': ce_loss, 'accuracy': accuracy,
                  'tokens': total_weight, 'aux_loss': aux_loss}


def _head_projection(params, model_config):
    """(kernel, einsum spec, softcap) for applying the model's head
    outside the model: llama/mixtral/untied-qwen expose lm_head
    [D, V]; the tied families (gemma/gpt2/tied-qwen) reuse tok_embed
    [V, D] — and gemma additionally softcaps the final logits."""
    if 'lm_head' in params:
        return params['lm_head']['kernel'], 'bcd,dv->bcv', None
    softcap = getattr(model_config, 'final_logit_softcap', None)
    return params['tok_embed'], 'bcd,vd->bcv', softcap or None


def _chunked_ce_sums(hidden: jax.Array, kernel: jax.Array,
                     targets: jax.Array, mask: jax.Array,
                     chunk: int, head_spec: str = 'bcd,dv->bcv',
                     softcap=None) -> Tuple[jax.Array, jax.Array]:
    """Masked CE sum + correct-prediction sum, lm_head applied per
    sequence chunk under jax.checkpoint, so at most [B, chunk, vocab]
    f32 logits are live at once (forward AND backward) instead of the
    full [B, S, vocab].  At long seq x large vocab the full logits are
    the single biggest buffer in the step — e.g. seq 8192, vocab 32k,
    batch 2: ~2.1 GB f32 that this scan never materializes."""
    b, s, d = hidden.shape
    assert s % chunk == 0, (s, chunk)
    n = s // chunk
    h = hidden.reshape(b, n, chunk, d).transpose(1, 0, 2, 3)
    t = targets.reshape(b, n, chunk).transpose(1, 0, 2)
    m = mask.reshape(b, n, chunk).transpose(1, 0, 2)

    @jax.checkpoint
    def body(carry, xs):
        h_c, t_c, m_c = xs
        # Mirrors the model head exactly: DenseGeneral dtype=f32 (or
        # the tied-embedding einsum) promotes both operands to f32.
        logits = jnp.einsum(head_spec, h_c.astype(jnp.float32),
                            kernel.astype(jnp.float32))
        if softcap:
            logits = softcap * jnp.tanh(logits / softcap)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits,
                                                             t_c)
        correct = ((jnp.argmax(logits, -1) == t_c) * m_c).sum()
        return (carry[0] + (ce * m_c).sum(),
                carry[1] + correct.astype(jnp.float32)), None

    (ce_sum, correct), _ = jax.lax.scan(
        body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        (h, t, m))
    return ce_sum, correct


def loss_fn_chunked(params, apply_fn, batch, *, chunk: int,
                    model_config=None
                    ) -> Tuple[jax.Array, Dict[str, Any]]:
    """loss_fn for models exposing `return_hidden` (every family):
    identical math, head applied chunk-by-chunk."""
    hidden, aux_loss = apply_fn({'params': params}, batch['inputs'],
                                return_hidden=True)
    kernel, head_spec, softcap = _head_projection(params, model_config)
    targets = batch['targets']
    mask = batch['mask']
    total_weight = jnp.maximum(mask.sum(), 1.0)
    ce_sum, correct = _chunked_ce_sums(hidden, kernel, targets, mask,
                                       chunk, head_spec, softcap)
    ce_loss = ce_sum / total_weight
    loss = ce_loss + aux_loss
    return loss, {'loss': ce_loss, 'accuracy': correct / total_weight,
                  'tokens': total_weight, 'aux_loss': aux_loss}


def train_step(state: TrainState, batch: Dict[str, jax.Array],
               grad_accum_steps: int = 1,
               train_only: Optional[str] = None,
               loss_chunk: int = 0,
               model_config=None
               ) -> Tuple[TrainState, Dict[str, jax.Array]]:
    base_loss_fn = (functools.partial(loss_fn_chunked, chunk=loss_chunk,
                                      model_config=model_config)
                    if loss_chunk else loss_fn)
    if train_only:
        # stop_gradient on frozen params: XLA then DCEs their weight-
        # gradient matmuls and buffers (LoRA's memory/FLOPs win), and
        # grad_norm below describes only the updates actually applied.
        freeze_mask = _trainable_mask(state.params, train_only)

        def loss_with_frozen(params, apply_fn, batch):
            mixed = jax.tree.map(
                lambda p, trainable: p if trainable
                else jax.lax.stop_gradient(p),
                params, freeze_mask)
            return base_loss_fn(mixed, apply_fn, batch)

        grad_fn = jax.value_and_grad(loss_with_frozen, has_aux=True)
    else:
        grad_fn = jax.value_and_grad(base_loss_fn, has_aux=True)

    if grad_accum_steps == 1:
        (_, metrics), grads = grad_fn(state.params, state.apply_fn, batch)
    else:
        def micro(carry, mb):
            grads_acc, metrics_acc = carry
            (_, metrics), grads = grad_fn(state.params, state.apply_fn,
                                          mb)
            grads_acc = jax.tree.map(jnp.add, grads_acc, grads)
            metrics_acc = jax.tree.map(jnp.add, metrics_acc, metrics)
            return (grads_acc, metrics_acc), None

        microbatches = jax.tree.map(
            lambda x: x.reshape(grad_accum_steps,
                                x.shape[0] // grad_accum_steps,
                                *x.shape[1:]), batch)
        zero_grads = jax.tree.map(jnp.zeros_like, state.params)
        zero_metrics = {'loss': jnp.float32(0), 'accuracy': jnp.float32(0),
                        'tokens': jnp.float32(0),
                        'aux_loss': jnp.float32(0)}
        (grads, metrics), _ = jax.lax.scan(
            micro, (zero_grads, zero_metrics), microbatches)
        grads = jax.tree.map(lambda g: g / grad_accum_steps, grads)
        metrics = jax.tree.map(lambda m: m / grad_accum_steps, metrics)

    updates, new_opt_state = state.tx.update(grads, state.opt_state,
                                             state.params)
    new_params = optax.apply_updates(state.params, updates)
    metrics['grad_norm'] = optax.global_norm(grads)
    return state.replace(step=state.step + 1, params=new_params,
                         opt_state=new_opt_state), metrics


def _train_metrics(registry=None):
    """Register (get-or-create) the trainer's telemetry instruments.

    Shared with the serving registry so one `/metrics` scrape covers a
    colocated trainer; import is local-ish (observability is stdlib-only)
    and the per-window update cost is a handful of dict ops.
    """
    from skypilot_tpu.observability import metrics as metrics_lib
    reg = registry if registry is not None else metrics_lib.get_registry()
    return {
        'step_seconds': reg.histogram(
            'skytpu_train_step_seconds',
            'Mean wall time per train step, observed once per log window.'),
        'tokens_per_sec': reg.gauge(
            'skytpu_train_tokens_per_sec',
            'Training throughput over the last log window.'),
        'steps': reg.counter('skytpu_train_steps_total',
                             'Optimizer steps completed.'),
        'tokens': reg.counter('skytpu_train_tokens_total',
                              'Tokens consumed by training.'),
        # Shared-name compile telemetry: the serving engine observes
        # the same two series with fn=decode/prefill, so one dashboard
        # query covers compile spend across both entry points.
        'jit_compiles': reg.counter(
            'skytpu_jit_compiles_total',
            'XLA compilations triggered, by jitted function.',
            labelnames=('fn',)),
        'jit_compile_seconds': reg.histogram(
            'skytpu_jit_compile_seconds',
            'Wall seconds spent in the first (compiling) call of a '
            'jitted function, by function.',
            labelnames=('fn',)),
    }


class Trainer:
    """Owns mesh, sharded state, and the jit'd step."""

    def __init__(self, config: TrainConfig,
                 mesh: Optional[Mesh] = None) -> None:
        import skypilot_tpu.models as models_lib
        self.config = config
        if config.compilation_cache_dir:
            mesh_lib.enable_persistent_compilation_cache(
                config.compilation_cache_dir)
        overrides = dict(config.model_overrides)
        context_size = (mesh.shape['context'] if mesh is not None
                        else config.mesh.context)
        if context_size > 1:
            # Context parallelism: sequence-sharded ring attention
            # unless the user pinned another implementation.
            overrides.setdefault('attention_impl', 'ring')
        self.model, self.model_config = models_lib.get_model(
            config.model, **overrides)
        self.mesh = mesh if mesh is not None else mesh_lib.make_mesh(
            config.mesh)
        tensor = self.mesh.shape['tensor']
        # Families without grouped KV (e.g. GPT-2) have no n_kv_heads.
        n_kv = getattr(self.model_config, 'n_kv_heads',
                       self.model_config.n_heads)
        if self.model_config.n_heads % tensor or n_kv % tensor:
            raise ValueError(
                f'tensor parallelism {tensor} must divide n_heads='
                f'{self.model_config.n_heads} and n_kv_heads={n_kv} '
                f'(model {self.model_config.name!r}).')
        n_batch = mesh_lib.num_batch_shards(self.mesh)
        micro = config.global_batch_size // max(config.grad_accum_steps, 1)
        if micro % n_batch:
            raise ValueError(
                f'per-step microbatch {micro} must be divisible by the '
                f'data*fsdp shards ({n_batch}).')
        n_context = self.mesh.shape['context']
        if n_context > 1 and config.seq_len % n_context:
            raise ValueError(
                f'context={n_context} must divide seq_len='
                f'{config.seq_len}.')
        if config.loss_chunk:
            import inspect
            call_params = inspect.signature(
                type(self.model).__call__).parameters
            if 'return_hidden' not in call_params:
                raise ValueError(
                    'loss_chunk requires a model exposing '
                    f'return_hidden; {config.model!r} does not.')
            if config.seq_len % config.loss_chunk:
                raise ValueError(
                    f'loss_chunk={config.loss_chunk} must divide '
                    f'seq_len={config.seq_len}.')
            if self.mesh.shape['pipe'] > 1:
                raise ValueError(
                    'loss_chunk does not yet compose with pipeline '
                    'parallelism (the PP path applies the head per '
                    'microbatch already).')
        n_pipe = self.mesh.shape['pipe']
        if n_pipe > 1:
            if hasattr(self.model_config, 'n_experts'):
                raise ValueError('pipeline parallelism does not yet '
                                 'compose with MoE models.')
            if not self.model_config.scan_layers:
                raise ValueError('pipeline parallelism requires '
                                 'scan_layers=True (stacked layer params).')
            repeats = config.pipeline_circular_repeats
            if repeats < 1:
                raise ValueError(
                    f'pipeline_circular_repeats must be >= 1, got '
                    f'{repeats}.')
            if self.model_config.n_layers % (n_pipe * repeats):
                raise ValueError(
                    f'pipe={n_pipe} x circular_repeats={repeats} must '
                    f'divide n_layers={self.model_config.n_layers}.')
            pp_micro = config.pipeline_microbatches or 2 * n_pipe
            if pp_micro < n_pipe or micro % pp_micro:
                raise ValueError(
                    f'pipeline microbatches {pp_micro} must be >= '
                    f'pipe={n_pipe} and divide the per-step batch '
                    f'{micro}.')
            self.pp_microbatches = pp_micro
        else:
            self.pp_microbatches = 0
        self.tx = make_optimizer(config)
        self._jit_step = None
        self.state: Optional[TrainState] = None
        self.state_shardings = None

    # -- init --------------------------------------------------------------
    def init_state(self) -> TrainState:
        cfg = self.config
        rng = jax.random.PRNGKey(cfg.seed)
        sample_tokens = jnp.zeros(
            (max(1, cfg.global_batch_size // cfg.grad_accum_steps),
             cfg.seq_len), jnp.int32)

        def _init(rng):
            variables = self.model.init(rng, sample_tokens)
            params = variables['params']
            opt_state = self.tx.init(sharding_lib.unbox(params))
            return params, opt_state

        abstract = jax.eval_shape(_init, rng)
        param_shardings = sharding_lib.params_to_shardings(
            self.mesh, abstract[0])
        unboxed_param_shardings = sharding_lib.unbox(param_shardings)

        def _like_params(tree):
            """Optimizer-state shardings: adam moments mirror params."""
            return jax.tree.map(
                lambda leaf: _match_leaf_sharding(leaf,
                                                  unboxed_param_shardings),
                tree,
                is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct))

        def _match_leaf_sharding(leaf, param_shardings_tree):
            # Heuristic: any opt-state leaf whose shape matches a param
            # leaf gets that param's sharding; scalars are replicated.
            flat_params = jax.tree.leaves(abstract[0])
            flat_shards = jax.tree.leaves(param_shardings_tree)
            for p, s in zip(flat_params, flat_shards):
                p_shape = getattr(p, 'value', p).shape
                if leaf.shape == p_shape:
                    return s
            return NamedSharding(self.mesh, P())

        opt_shardings = _like_params(abstract[1])
        init_jit = jax.jit(_init, out_shardings=(param_shardings,
                                                 opt_shardings))
        with self.mesh:
            params, opt_state = init_jit(rng)
        params = sharding_lib.unbox(params)
        self.state = TrainState(step=jnp.zeros((), jnp.int32),
                                params=params, opt_state=opt_state,
                                apply_fn=self._apply_unboxed,
                                tx=self.tx)
        self.state_shardings = TrainState(
            step=NamedSharding(self.mesh, P()),
            params=sharding_lib.unbox(param_shardings),
            opt_state=opt_shardings,
            apply_fn=self._apply_unboxed, tx=self.tx)
        return self.state

    def _apply_unboxed(self, variables, tokens, return_hidden=False):
        """Returns (logits_or_hidden, aux_loss)."""
        if self.pp_microbatches:
            assert not return_hidden  # rejected in __init__
            return (self._pipelined_apply(variables['params'], tokens),
                    jnp.zeros((), jnp.float32))
        # Only pass the kwarg when set (keeps third-party models
        # without a return_hidden parameter working for the normal
        # logits path).
        kwargs = {'return_hidden': True} if return_hidden else {}
        if hasattr(self.model_config, 'n_experts'):
            # MoE: collect the sown router load-balance losses.
            out, mutated = self.model.apply(
                variables, tokens, mutable=['intermediates'], **kwargs)
            return out, sum_aux_losses(mutated)
        return (self.model.apply(variables, tokens, **kwargs),
                jnp.zeros((), jnp.float32))

    def _pipelined_apply(self, params, tokens):
        """Forward with the decoder blocks run as a GPipe pipeline over
        the `pipe` mesh axis (embed / final norm / lm_head stay in the
        surrounding auto-sharded graph).

        Composes with context parallelism: the pipeline shard_map is
        then manual over {'pipe','context'}, the microbatch buffer is
        sequence-sharded, stages compute GLOBAL RoPE positions from
        their context index, and the in-block ring attention runs
        directly on the local shards (ops/ring_attention.py detects the
        manual region)."""
        from jax.sharding import PartitionSpec as P

        from skypilot_tpu.parallel import pipeline as pipeline_lib

        cfg = dataclasses.replace(self.model_config,
                                  partition_params=False)
        n_context = self.mesh.shape['context']
        if (n_context > 1 and jax.default_backend() != 'tpu'
                and jnp.dtype(cfg.dtype) in (jnp.bfloat16, jnp.float16)):
            # The XLA CPU backend aborts ("Invalid binary instruction
            # opcode copy") on bf16 compute nested inside the
            # {pipe, context} partial-manual region; stages run f32
            # off-TPU (same class of workaround as
            # parallel/pipeline.py's f32 boundary). TPU stays bf16.
            cfg = dataclasses.replace(cfg, dtype=jnp.float32)
        x = llama.embed_lookup(cfg, params['tok_embed'], tokens)
        block = llama.Block(cfg)

        def block_apply(layer_params, h, pos):
            return block.apply({'params': layer_params}, h, pos)

        if cfg.remat:
            block_apply = jax.checkpoint(
                block_apply,
                policy=jax.checkpoint_policies.nothing_saveable)

        def stage_fn(local_layers, mb):
            s_local = mb.shape[1]
            offset = 0
            if n_context > 1:
                offset = jax.lax.axis_index('context') * s_local
            pos = jnp.broadcast_to(
                offset + jnp.arange(s_local, dtype=jnp.int32)[None],
                mb.shape[:2])
            return jax.lax.scan(
                lambda h, lp: (block_apply(lp, h, pos), None),
                mb, local_layers)[0]

        extra_axes = frozenset({'context'}) if n_context > 1 \
            else frozenset()
        # mbs: [M, mbb, seq, dim] — sequence sharded over context.
        mb_spec = P(None, None, 'context', None) if n_context > 1 \
            else P()
        mbs = pipeline_lib.microbatch(x, self.pp_microbatches)
        x = pipeline_lib.unmicrobatch(
            pipeline_lib.gpipe(
                stage_fn, params['layers'], mbs, mesh=self.mesh,
                extra_manual_axes=extra_axes, mb_spec=mb_spec,
                circular_repeats=self.config.pipeline_circular_repeats))
        return llama.apply_final_head(cfg, params['final_norm'],
                                      params['lm_head'], x)

    # -- stepping ----------------------------------------------------------
    def compiled_step(self):
        if self._jit_step is None:
            assert self.state_shardings is not None
            batch_sharding = {
                'inputs': sharding_lib.batch_sharding(self.mesh),
                'targets': sharding_lib.batch_sharding(self.mesh),
                'mask': sharding_lib.batch_sharding(self.mesh),
            }
            self._jit_step = jax.jit(
                functools.partial(
                    train_step,
                    grad_accum_steps=self.config.grad_accum_steps,
                    train_only=self.config.train_only,
                    loss_chunk=self.config.loss_chunk,
                    model_config=self.model_config),
                in_shardings=(self.state_shardings, batch_sharding),
                out_shardings=(self.state_shardings, None),
                donate_argnums=(0,),
            )
        return self._jit_step

    def step(self, batch) -> Dict[str, jax.Array]:
        assert self.state is not None, 'call init_state() first'
        with self.mesh:
            self.state, metrics = self.compiled_step()(self.state, batch)
        return metrics

    # -- loop --------------------------------------------------------------
    def train(self, data_iter: Iterator[Dict[str, jax.Array]],
              num_steps: Optional[int] = None,
              log_every: int = 10,
              checkpoint_manager=None,
              checkpoint_every: int = 0) -> Dict[str, float]:
        import os

        from skypilot_tpu import callbacks
        cfg = self.config
        if self.state is None:
            self.init_state()
        steps = num_steps if num_steps is not None else cfg.total_steps
        tokens_per_step = cfg.global_batch_size * cfg.seq_len
        # Double-buffered input: host gen + host->device transfer of
        # batch N+1 overlaps step N's compute (train/data.py
        # prefetch_to_device).  CONTRACT: the producer thread reads up
        # to depth+1 batches past the last consumed step, so a caller
        # that reuses `data_iter` after train() returns would skip
        # them — set SKYTPU_PREFETCH_DEPTH=0 for that pattern (or any
        # test that counts batches).
        prefetch_depth = int(os.environ.get('SKYTPU_PREFETCH_DEPTH',
                                            '2'))
        if prefetch_depth > 0:
            from skypilot_tpu.train import data as data_lib
            data_iter = data_lib.prefetch_to_device(data_iter,
                                                    prefetch_depth)
        # Workload profiling (the TPU analog of what the reference
        # delegates to user tools): SKYTPU_PROFILE_DIR=<dir> (or
        # SKYTPU_PROFILE=1 to write under the job log dir) captures an
        # XLA trace of a few steady-state steps, viewable in
        # TensorBoard/Perfetto.
        profile_dir = os.environ.get('SKYTPU_PROFILE_DIR', '')
        if not profile_dir and os.environ.get('SKYTPU_PROFILE') == '1':
            profile_dir = os.path.join(
                os.environ.get('SKYTPU_LOG_DIR', os.getcwd()), 'profile')
        if jax.process_index() != 0:
            profile_dir = ''
        # Skip the compile step so the trace shows steady-state compute.
        prof_start = 1 if steps > 1 else 0
        prof_stop = min(prof_start + 3, steps)
        profiling = False
        # Step-log only from process 0: every rank of a multi-host job
        # inherits the same log path, and interleaved per-rank records
        # would corrupt the harness's sec/step medians.
        bench_logger = (callbacks.BenchmarkLogger.maybe_from_env()
                        if jax.process_index() == 0 else None)
        # Telemetry rides the same once-per-window cadence as the step
        # log, so it adds no per-step host work (process 0 only — same
        # rationale as bench_logger above).
        telemetry = (_train_metrics()
                     if jax.process_index() == 0 else None)
        t0 = time.time()
        window_tokens = 0
        window_start_step = 0
        last: Dict[str, float] = {}
        try:
            for i in range(steps):
                if profile_dir and i == prof_start:
                    jax.profiler.start_trace(profile_dir)
                    profiling = True
                batch = next(data_iter)
                # First call pays the jit trace+compile synchronously
                # before dispatch returns — its wall time IS the
                # compile time (steady-state dispatch is ~ms).
                compiling = telemetry is not None and i == 0
                t_step = time.perf_counter() if compiling else 0.0
                metrics = self.step(batch)
                if compiling:
                    telemetry['jit_compiles'].labels(
                        fn='train_step').inc()
                    telemetry['jit_compile_seconds'].labels(
                        fn='train_step').observe(
                            time.perf_counter() - t_step)
                if profiling and i + 1 == prof_stop:
                    jax.device_get(metrics['loss'])  # drain async work
                    jax.profiler.stop_trace()
                    profiling = False
                window_tokens += tokens_per_step
                if bench_logger is not None:
                    bench_logger.log_step(i + 1)
                if (i + 1) % log_every == 0 or i + 1 == steps:
                    metrics = jax.device_get(metrics)
                    dt = time.time() - t0
                    tps = window_tokens / dt if dt > 0 else 0.0
                    last = {
                        'step': int(self.state.step),
                        'loss': float(metrics['loss']),
                        'accuracy': float(metrics['accuracy']),
                        'grad_norm': float(metrics['grad_norm']),
                        'tokens_per_sec': tps,
                    }
                    logger.info(
                        f'step {last["step"]} loss {last["loss"]:.4f} '
                        f'acc {last["accuracy"]:.3f} {tps:,.0f} tok/s')
                    if telemetry is not None:
                        window_steps = (i + 1) - window_start_step
                        if window_steps > 0 and dt > 0:
                            telemetry['step_seconds'].observe(
                                dt / window_steps)
                        telemetry['tokens_per_sec'].set(tps)
                        telemetry['steps'].inc(window_steps)
                        telemetry['tokens'].inc(window_tokens)
                    t0 = time.time()
                    window_tokens = 0
                    window_start_step = i + 1
                if checkpoint_manager is not None and checkpoint_every and \
                        (i + 1) % checkpoint_every == 0:
                    from skypilot_tpu.train import checkpoint as ckpt_lib
                    ckpt_lib.save(checkpoint_manager, self.state)
        finally:
            if profiling:
                jax.profiler.stop_trace()
        return last
