"""Training data pipelines: per-host sharded batches onto the mesh.

Two sources:
  - `synthetic_data`: deterministic token stream (benchmarks and tests —
    same role as the reference's torch_ddp_benchmark synthetic inputs);
  - `hf_text_data`: HuggingFace datasets + tokenizer packing (the llm/
    recipe path), gated on the libraries being present.

Every iterator yields GLOBAL batches as jax.Arrays already sharded over
the mesh's batch axes: each host materializes only its local shard and
`jax.make_array_from_process_local_data` assembles the global view.
"""
from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)


def _global_batch(mesh: Mesh, local: Dict[str, np.ndarray]
                  ) -> Dict[str, jax.Array]:
    sharding = NamedSharding(mesh, P(('data', 'fsdp')))
    return {
        key: jax.make_array_from_process_local_data(sharding, value)
        for key, value in local.items()
    }


def synthetic_data(mesh: Mesh, *, global_batch_size: int, seq_len: int,
                   vocab_size: int, seed: int = 0, start_step: int = 0
                   ) -> Iterator[Dict[str, jax.Array]]:
    """Infinite deterministic LM batches: inputs + next-token targets.

    Per-step counter-based seeding makes resume token-exact and O(1):
    a recovered job passes `start_step` (its restored step) and sees
    exactly the batches the lost run would have seen next.
    """
    num_hosts = jax.process_count()
    if global_batch_size % num_hosts != 0:
        raise ValueError(
            f'global_batch_size {global_batch_size} not divisible by '
            f'{num_hosts} hosts.')
    local_bs = global_batch_size // num_hosts
    step = start_step
    while True:
        rng = np.random.default_rng(
            (seed, jax.process_index(), step))
        tokens = rng.integers(1, vocab_size, (local_bs, seq_len + 1),
                              dtype=np.int32)
        step += 1
        yield _global_batch(mesh, {
            'inputs': tokens[:, :-1],
            'targets': tokens[:, 1:],
            'mask': np.ones((local_bs, seq_len), np.float32),
        })


def hf_text_data(mesh: Mesh, *, dataset_name: str, tokenizer_name: str,
                 global_batch_size: int, seq_len: int,
                 split: str = 'train', text_field: str = 'text',
                 seed: int = 0, start_step: int = 0
                 ) -> Iterator[Dict[str, jax.Array]]:
    """Packed-causal-LM batches from a HF dataset (each host streams its
    own shard — per-host sharded loading, SURVEY.md §2.11 'per-host
    sharded data loading').

    `start_step` fast-forwards the packed stream past the sequences a
    resumed job already consumed — token-exact given the same
    dataset/seed (it replays tokenization for the skipped prefix, so
    resume cost is IO/tokenizer time, not training time).
    """
    try:
        import datasets  # type: ignore
        from transformers import AutoTokenizer  # type: ignore
    except ImportError as e:
        raise RuntimeError(
            'hf_text_data requires `datasets` and `transformers`.') from e
    num_hosts = jax.process_count()
    local_bs = global_batch_size // num_hosts
    tokenizer = AutoTokenizer.from_pretrained(tokenizer_name)
    ds = datasets.load_dataset(dataset_name, split=split, streaming=True)
    ds = ds.shard(num_shards=num_hosts, index=jax.process_index())
    ds = ds.shuffle(seed=seed, buffer_size=10_000)

    def packed() -> Iterator[np.ndarray]:
        buffer: list = []
        for example in ds:
            buffer.extend(tokenizer(example[text_field])['input_ids'])
            buffer.append(tokenizer.eos_token_id or 0)
            while len(buffer) >= seq_len + 1:
                yield np.asarray(buffer[:seq_len + 1], np.int32)
                buffer = buffer[seq_len:]

    stream = packed()
    if start_step > 0:
        skip = start_step * local_bs
        logger.info(f'Resuming data stream: skipping {skip} packed '
                    f'sequences ({start_step} steps).')
        for i in range(skip):
            try:
                next(stream)
            except StopIteration:
                raise RuntimeError(
                    f'Dataset {dataset_name!r} exhausted during '
                    f'resume fast-forward after {i}/{skip} packed '
                    'sequences — did the dataset, split, or host '
                    'count change since the checkpoint was written?'
                ) from None
    while True:
        rows = [next(stream) for _ in range(local_bs)]
        tokens = np.stack(rows)
        yield _global_batch(mesh, {
            'inputs': tokens[:, :-1],
            'targets': tokens[:, 1:],
            'mask': np.ones((local_bs, seq_len), np.float32),
        })


def prefetch_to_device(it: Iterator[Dict[str, jax.Array]], depth: int = 2
                       ) -> Iterator[Dict[str, jax.Array]]:
    """Overlap host batch generation + host->device transfer with
    compute: a daemon thread runs the wrapped iterator (whose
    `_global_batch` transfer can block for a full RTT on tunneled or
    DCN-attached devices) up to `depth` batches ahead.

    The standard TPU input-pipeline pattern (MaxText-style double
    buffering): while step N runs on device, batch N+1 is already in
    HBM and N+2 is in flight.  Token-exact resume is unaffected --
    iterators are recreated from the restored step counter, and
    batches prefetched but never consumed are simply dropped with the
    thread.  The producer propagates its exceptions to the consumer,
    and shuts down promptly when the consumer abandons the iterator
    early (finite train() runs, GeneratorExit): every queue put polls a
    stop event, so the thread never blocks forever on a full queue that
    nobody will drain again."""
    if depth <= 0:
        yield from it
        return
    q: 'queue.Queue' = queue.Queue(maxsize=depth)
    sentinel = object()
    stop = threading.Event()

    def _put(item) -> bool:
        """Blocking put that gives up when the consumer is gone."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer() -> None:
        try:
            for batch in it:
                if not _put(batch):
                    return
        except BaseException as e:  # noqa: BLE001 — re-raised below
            _put((sentinel, e))
            return
        _put((sentinel, None))

    thread = threading.Thread(target=producer, daemon=True,
                              name='skytpu-data-prefetch')
    thread.start()
    try:
        while True:
            item = q.get()
            if isinstance(item, tuple) and len(item) == 2 \
                    and item[0] is sentinel:
                if item[1] is not None:
                    raise item[1]
                return
            yield item
    finally:
        # Runs on exhaustion AND on early abandonment (GeneratorExit /
        # gc of a half-consumed generator): release the producer if it
        # is blocked on a full queue, then reap the thread.
        stop.set()
        while True:
            try:
                q.get_nowait()
            except queue.Empty:
                break
        thread.join(timeout=5)
