"""Train entrypoint: `python -m skypilot_tpu.train --model llama3-8b ...`

The workload that task YAMLs gang-run on slices (the JAX analog of the
reference's llm/llama-3_1-finetuning torchtune command).  Initializes
jax.distributed from the gang driver's env contract, builds the mesh over
all devices, trains, optionally checkpointing to a (bucket-mounted) dir.
"""
from __future__ import annotations

import argparse
import json


def main() -> None:
    parser = argparse.ArgumentParser(description='skypilot_tpu trainer')
    parser.add_argument('--model', default='llama-tiny')
    parser.add_argument('--steps', type=int, default=100)
    parser.add_argument('--global-batch-size', type=int, default=8)
    parser.add_argument('--seq-len', type=int, default=512)
    parser.add_argument('--learning-rate', type=float, default=3e-4)
    parser.add_argument('--grad-accum-steps', type=int, default=1)
    parser.add_argument('--mesh', default='fsdp=-1',
                        help="e.g. 'data=2,fsdp=-1,pipe=2,tensor=4'")
    parser.add_argument('--pipeline-microbatches', type=int,
                        default=None,
                        help='GPipe microbatches when pipe>1 '
                             '(default: 2*pipe).')
    parser.add_argument('--checkpoint-dir', default=None)
    parser.add_argument('--checkpoint-every', type=int, default=0)
    parser.add_argument('--compilation-cache-dir', default=None,
                        help='Persistent XLA compile cache: repeat/'
                             'recovered runs skip the first-step '
                             'compile. Point at the bucket-mounted '
                             'checkpoint dir for preemption recovery.')
    parser.add_argument('--dataset', default=None,
                        help='HF dataset (default: synthetic).')
    parser.add_argument('--tokenizer', default=None)
    parser.add_argument('--log-every', type=int, default=10)
    parser.add_argument('--json-metrics', action='store_true',
                        help='Print final metrics as one JSON line '
                             '(adds params/device info for benchmark '
                             'normalization).')
    parser.add_argument('--loss-chunk', type=int, default=0,
                        help='Chunked cross-entropy: apply the lm_head '
                             'per this many sequence tokens so the '
                             'full [B,S,vocab] f32 logits never '
                             'materialize (0 = off; llama/mixtral).')
    parser.add_argument('--train-only', default=None,
                        help='Train only params whose path contains '
                             "this substring (e.g. 'lora'); the rest "
                             'are frozen.')
    parser.add_argument('--platform', default=None,
                        help="Force a jax platform (e.g. 'cpu' for "
                             'smoke runs; env JAX_PLATFORMS alone is '
                             'not enough on tunneled-TPU hosts).')
    parser.add_argument('--model-overrides', default=None,
                        help='JSON dict of model-config overrides, '
                             "e.g. '{\"dim\": 1536, \"n_layers\": 12}'")
    args = parser.parse_args()

    # Honor --platform / an explicit JAX_PLATFORMS even when the
    # interpreter's sitecustomize captured a different platform at
    # startup (this environment pins 'axon'); same recipe as
    # tests/conftest.py.
    import os
    plat = args.platform or os.environ.get('JAX_PLATFORMS')
    # (The single-platform guard only applies to the ambient env var;
    # an explicit --platform, comma list or not, is always honored.)
    if args.platform or (plat and ',' not in plat):
        import jax
        jax.config.update('jax_platforms', plat)

    from skypilot_tpu.train import launcher
    launcher.maybe_initialize_distributed()

    from skypilot_tpu.parallel import mesh as mesh_lib
    from skypilot_tpu.train import data as data_lib
    from skypilot_tpu.train import trainer as trainer_lib

    mesh_kwargs = {}
    for part in args.mesh.split(','):
        if part:
            k, v = part.split('=')
            mesh_kwargs[k] = int(v)
    overrides = {'max_seq_len': args.seq_len}
    if args.model_overrides:
        overrides.update(json.loads(args.model_overrides))
    config = trainer_lib.TrainConfig(
        model=args.model,
        global_batch_size=args.global_batch_size,
        seq_len=args.seq_len,
        learning_rate=args.learning_rate,
        grad_accum_steps=args.grad_accum_steps,
        total_steps=args.steps,
        mesh=mesh_lib.MeshConfig(**mesh_kwargs),
        pipeline_microbatches=args.pipeline_microbatches,
        model_overrides=overrides,
        train_only=args.train_only,
        compilation_cache_dir=args.compilation_cache_dir,
        loss_chunk=args.loss_chunk,
    )
    trainer = trainer_lib.Trainer(config)
    manager = None
    if args.checkpoint_dir:
        from skypilot_tpu.train import checkpoint as ckpt_lib
        manager = ckpt_lib.make_manager(args.checkpoint_dir)
        ckpt_lib.restore_or_init(manager, trainer)
    else:
        trainer.init_state()

    # Resume token-exact: a recovered job's data stream starts where
    # the lost run's left off (the managed-jobs checkpoint contract).
    start_step = int(trainer.state.step)
    if args.dataset:
        data_iter = data_lib.hf_text_data(
            trainer.mesh, dataset_name=args.dataset,
            tokenizer_name=args.tokenizer or args.dataset,
            global_batch_size=config.global_batch_size,
            seq_len=config.seq_len, start_step=start_step)
    else:
        data_iter = data_lib.synthetic_data(
            trainer.mesh, global_batch_size=config.global_batch_size,
            seq_len=config.seq_len,
            vocab_size=trainer.model_config.vocab_size,
            start_step=start_step)

    remaining = args.steps - int(trainer.state.step)
    metrics = trainer.train(data_iter, num_steps=max(remaining, 0),
                            log_every=args.log_every,
                            checkpoint_manager=manager,
                            checkpoint_every=args.checkpoint_every)
    if manager is not None:
        from skypilot_tpu.train import checkpoint as ckpt_lib
        ckpt_lib.save(manager, trainer.state, wait=True)
    if args.json_metrics:
        import jax

        from skypilot_tpu import models as models_lib
        metrics = dict(metrics)
        try:
            n_params = models_lib.num_params(trainer.model_config)
        except (TypeError, AttributeError):
            n_params = sum(
                x.size for x in jax.tree.leaves(trainer.state.params))
        metrics.update({
            'n_params': n_params,
            'n_devices': len(jax.devices()),
            'device_kind': jax.devices()[0].device_kind,
            'global_batch_size': config.global_batch_size,
            'seq_len': config.seq_len,
        })
        print('SKYTPU_METRICS ' + json.dumps(metrics), flush=True)


if __name__ == '__main__':
    main()
