"""Async multi-host checkpointing (Orbax) — the resume half of the
bucket-checkpoint contract.

The reference's recovery story is "write checkpoints to a bucket-mounted
dir, recovered jobs resume from it" (SURVEY.md §5, llm/llama-3_1-
finetuning/lora.yaml:24-30); managed TPU jobs here follow the same
contract with first-class async Orbax saves: every host writes its own
param shards (OCDBT), so a v5p-128 checkpoint scales with hosts, and
`restore_or_init` makes the trainer preemption-transparent.
"""
from __future__ import annotations

import os
from typing import Any, Optional

import jax

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)


def make_manager(directory: str, *, max_to_keep: int = 3,
                 save_interval_steps: int = 0):
    import orbax.checkpoint as ocp
    directory = os.path.abspath(os.path.expanduser(directory)) \
        if '://' not in directory else directory
    options = ocp.CheckpointManagerOptions(
        max_to_keep=max_to_keep,
        enable_async_checkpointing=True,
    )
    return ocp.CheckpointManager(directory, options=options)


def save(manager, state, *, wait: bool = False) -> int:
    import orbax.checkpoint as ocp
    step = int(jax.device_get(state.step))
    manager.save(step, args=ocp.args.Composite(
        state=ocp.args.StandardSave({'params': state.params,
                                     'opt_state': state.opt_state,
                                     'step': state.step})))
    if wait:
        manager.wait_until_finished()
    logger.info(f'Checkpoint step {step} saved (async).')
    return step


def restore(manager, state):
    """Restore into the sharded structure of `state` (shapes/shardings
    from the live state; works across host counts)."""
    import orbax.checkpoint as ocp
    latest = manager.latest_step()
    if latest is None:
        return None
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        if isinstance(x, jax.Array) else x,
        {'params': state.params, 'opt_state': state.opt_state,
         'step': state.step})
    restored = manager.restore(
        latest, args=ocp.args.Composite(
            state=ocp.args.StandardRestore(abstract)))['state']
    logger.info(f'Restored checkpoint step {latest}.')
    return state.replace(step=restored['step'], params=restored['params'],
                         opt_state=restored['opt_state'])


def restore_params_partial(manager, state):
    """Base-weights restore into a *different* live tree: every saved
    param whose path+shape matches the live params is loaded; the rest
    (e.g. fresh LoRA adapters) keep their init, and optimizer state is
    rebuilt fresh at step 0.  This is what lets the LoRA recipe start
    from a pretrained base checkpoint saved without adapters."""
    import flax
    import orbax.checkpoint as ocp
    latest = manager.latest_step()
    if latest is None:
        return None
    # Untyped restore of the saved params subtree only.
    raw = manager.restore(
        latest, args=ocp.args.Composite(state=ocp.args.StandardRestore())
    )['state']
    saved = flax.traverse_util.flatten_dict(raw['params'])
    live = flax.traverse_util.flatten_dict(state.params)
    merged, loaded, skipped = {}, 0, []
    for key, value in live.items():
        sv = saved.get(key)
        if sv is not None and tuple(sv.shape) == tuple(value.shape):
            merged[key] = jax.device_put(
                jax.numpy.asarray(sv, dtype=value.dtype), value.sharding)
            loaded += 1
        else:
            merged[key] = value
            skipped.append('/'.join(map(str, key)))
    params = flax.traverse_util.unflatten_dict(merged)
    logger.info(
        f'Partial restore from step {latest}: {loaded} params loaded, '
        f'{len(skipped)} kept from init '
        f'(e.g. {skipped[:3]}); optimizer state reset.')
    return state.replace(params=params,
                         opt_state=state.tx.init(params),
                         step=jax.numpy.zeros_like(state.step))


def restore_or_init(manager, trainer) -> Any:
    """Preemption-transparent init: restore latest if present, else fresh
    init (the managed-jobs recovery contract).  A checkpoint whose tree
    does not match the live state (a base checkpoint opened by a LoRA/
    frozen-finetune config) falls back to a params-only partial
    restore."""
    state = trainer.init_state()
    try:
        restored = restore(manager, state)
    except Exception as e:  # noqa: BLE001 — orbax raises various types
        if manager.latest_step() is None:
            raise
        logger.info(f'Exact-tree restore failed ({type(e).__name__}); '
                    'attempting params-only partial restore.')
        restored = restore_params_partial(manager, state)
    if restored is not None:
        trainer.state = restored
        return restored
    return state
