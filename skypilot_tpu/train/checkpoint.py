"""Async multi-host checkpointing (Orbax) — the resume half of the
bucket-checkpoint contract.

The reference's recovery story is "write checkpoints to a bucket-mounted
dir, recovered jobs resume from it" (SURVEY.md §5, llm/llama-3_1-
finetuning/lora.yaml:24-30); managed TPU jobs here follow the same
contract with first-class async Orbax saves: every host writes its own
param shards (OCDBT), so a v5p-128 checkpoint scales with hosts, and
`restore_or_init` makes the trainer preemption-transparent.

Layout: params / opt_state / step are separate Composite items, so a
*base* checkpoint's params can be restored sharded into a different
live tree (LoRA finetune from pretrained weights) without touching its
optimizer state.
"""
from __future__ import annotations

import os
from typing import Any, Optional

import jax

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)


def make_manager(directory: str, *, max_to_keep: int = 3,
                 save_interval_steps: int = 0):
    import orbax.checkpoint as ocp
    directory = os.path.abspath(os.path.expanduser(directory)) \
        if '://' not in directory else directory
    options = ocp.CheckpointManagerOptions(
        max_to_keep=max_to_keep,
        enable_async_checkpointing=True,
    )
    # Declared item layout + handlers: a fresh process (e.g. a LoRA
    # finetune opening a base checkpoint it never wrote) can then read
    # item_metadata without having saved first.
    return ocp.CheckpointManager(
        directory, options=options,
        item_handlers={
            'params': ocp.StandardCheckpointHandler(),
            'opt_state': ocp.StandardCheckpointHandler(),
            'step': ocp.ArrayCheckpointHandler(),
            # Pre-split layout (single 'state' item) — read-only
            # compatibility for checkpoints written by earlier builds.
            # PyTreeCheckpointHandler (same on-disk format Standard*
            # wraps) so partial_restore can pull just the params.
            'state': ocp.PyTreeCheckpointHandler(),
        })


def _is_legacy_layout(manager, step: int) -> bool:
    """True when the checkpoint was written as one Composite 'state'
    item (the pre-split layout).  Item metadata works for local AND
    bucket (gs://, s3://) directories; the os.path probe is only a
    fallback."""
    try:
        meta = manager.item_metadata(step)
        has_state = meta['state'] is not None
        has_params = meta['params'] is not None
        if has_state or has_params:
            return has_state and not has_params
    except Exception:  # noqa: BLE001 — fall through to the path probe
        pass
    try:
        d = manager.directory
    except AttributeError:
        return False
    step_dir = os.path.join(str(d), str(step))
    return (os.path.isdir(os.path.join(step_dir, 'state'))
            and not os.path.isdir(os.path.join(step_dir, 'params')))


def save(manager, state, *, wait: bool = False) -> int:
    import orbax.checkpoint as ocp
    step = int(jax.device_get(state.step))
    manager.save(step, args=ocp.args.Composite(
        params=ocp.args.StandardSave(state.params),
        opt_state=ocp.args.StandardSave(state.opt_state),
        step=ocp.args.ArraySave(state.step)))
    if wait:
        manager.wait_until_finished()
    logger.info(f'Checkpoint step {step} saved (async).')
    return step


def _abstract(tree):
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                       sharding=x.sharding)
        if isinstance(x, jax.Array) else x, tree)


def restore(manager, state):
    """Exact restore into the sharded structure of `state` (shapes/
    shardings from the live state; works across host counts).  Raises
    on any failure — a broken resume must be loud, not a silent
    restart."""
    import orbax.checkpoint as ocp
    latest = manager.latest_step()
    if latest is None:
        return None
    if _is_legacy_layout(manager, latest):
        # PyTreeRestore's `item` alone does not carry shardings into
        # the array handler (same gotcha as ArrayRestore): explicit
        # restore_args or Orbax reads the checkpoint's sharding file.
        item = {
            'params': _abstract(state.params),
            'opt_state': _abstract(state.opt_state),
            'step': _abstract(state.step),
        }
        restored = manager.restore(
            latest, args=ocp.args.Composite(
                state=ocp.args.PyTreeRestore(
                    item=item,
                    restore_args=ocp.checkpoint_utils
                    .construct_restore_args(item))))['state']
    else:
        restored = manager.restore(
            latest, args=ocp.args.Composite(
                params=ocp.args.StandardRestore(_abstract(state.params)),
                opt_state=ocp.args.StandardRestore(
                    _abstract(state.opt_state)),
                # ArrayRestore's `item` is ignored for sharding; the
                # explicit sharding must ride restore_args or Orbax
                # falls back to the checkpoint's sharding FILE —
                # unsafe when resuming on a different topology (the
                # managed-jobs recovery shape).
                step=ocp.args.ArrayRestore(
                    restore_args=ocp.type_handlers.ArrayRestoreArgs(
                        sharding=state.step.sharding,
                        global_shape=state.step.shape,
                        dtype=state.step.dtype))))
    logger.info(f'Restored checkpoint step {latest}.')
    return state.replace(step=restored['step'],
                         params=restored['params'],
                         opt_state=restored['opt_state'])


def _flatten_metadata(meta):
    """Orbax metadata tree -> {path_tuple: ArrayMetadata} with flax-
    style string-key paths (metadata impls are pytrees but not plain
    dicts)."""
    import jax.tree_util as jtu
    out = {}
    for path, leaf in jtu.tree_flatten_with_path(meta)[0]:
        key = tuple(
            str(getattr(p, 'key', getattr(p, 'name', p))) for p in path)
        out[key] = leaf
    return out


def _ensure_shardings(tree):
    """Attach a SingleDeviceSharding to any abstract leaf that lacks
    one — every restore must carry an explicit sharding (never the
    checkpoint's sharding file; wrong topology on recovery)."""
    default = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    return jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                       sharding=default)
        if isinstance(a, jax.ShapeDtypeStruct)
        and getattr(a, 'sharding', None) is None else a,
        tree)


def load_params_for_serving(manager, abstract_params,
                            step: Optional[int] = None):
    """Params-only load for the inference engine: abstract_params is a
    tree of ShapeDtypeStructs (with serving shardings; leaves without
    one default to the first device); handles both the split layout
    and the legacy single-'state' layout."""
    import orbax.checkpoint as ocp
    abstract_params = _ensure_shardings(abstract_params)
    latest = step if step is not None else manager.latest_step()
    if latest is None:
        raise FileNotFoundError('no checkpoint step found')
    if _is_legacy_layout(manager, latest):
        # Legacy: params live inside the 'state' item.  partial_restore
        # pulls ONLY the params subtree — a serving host sized for the
        # params must not materialize the (2x larger) optimizer state.
        item = {'params': abstract_params}
        restored = manager.restore(
            latest, args=ocp.args.Composite(
                state=ocp.args.PyTreeRestore(
                    item=item,
                    restore_args=ocp.checkpoint_utils
                    .construct_restore_args(item),
                    partial_restore=True)))['state']
        return restored['params']
    restored = manager.restore(
        latest, args=ocp.args.Composite(
            params=ocp.args.StandardRestore(abstract_params)))
    return restored['params']


def restore_params_partial(manager, state):
    """Base-weights restore into a *different* live tree: every saved
    param whose path+shape matches the live params is restored WITH the
    live sharding (host-sharded OCDBT read); the rest (e.g. fresh LoRA
    adapters) keep their init.  Optimizer state is rebuilt fresh at
    step 0 — this is a finetune start, not a resume."""
    import flax
    import orbax.checkpoint as ocp
    latest = manager.latest_step()
    if latest is None:
        return None
    meta = manager.item_metadata(latest)['params']
    saved_meta = _flatten_metadata(meta)
    live = flax.traverse_util.flatten_dict(state.params)
    # Saved params with no live counterpart restore replicated — but
    # still with an EXPLICIT sharding, never the checkpoint's sharding
    # file (wrong topology on recovery, and Orbax warns).
    replicated = None
    for lv in live.values():
        s = getattr(lv, 'sharding', None)
        if isinstance(s, jax.sharding.NamedSharding):
            replicated = jax.sharding.NamedSharding(
                s.mesh, jax.sharding.PartitionSpec())
            break
    if replicated is None:
        replicated = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    abstract = {}
    for key, m in saved_meta.items():
        lv = live.get(key)
        if lv is not None and tuple(m.shape) == tuple(lv.shape):
            abstract[key] = jax.ShapeDtypeStruct(
                lv.shape, lv.dtype, sharding=lv.sharding)
        else:
            abstract[key] = jax.ShapeDtypeStruct(
                tuple(m.shape), m.dtype, sharding=replicated)
    restored = flax.traverse_util.flatten_dict(
        manager.restore(
            latest, args=ocp.args.Composite(
                params=ocp.args.StandardRestore(
                    flax.traverse_util.unflatten_dict(abstract))))
        ['params'])
    merged, loaded, kept = {}, 0, []
    for key, value in live.items():
        sv = restored.get(key)
        if sv is not None and tuple(sv.shape) == tuple(value.shape):
            merged[key] = sv
            loaded += 1
        else:
            merged[key] = value
            kept.append('/'.join(map(str, key)))
    params = flax.traverse_util.unflatten_dict(merged)
    logger.info(
        f'Partial restore from step {latest}: {loaded} params loaded, '
        f'{len(kept)} kept from init (e.g. {kept[:3]}); optimizer '
        'state reset, step reset to 0.')
    return state.replace(params=params,
                         opt_state=state.tx.init(params),
                         step=jax.numpy.zeros_like(state.step))


def restore_or_init(manager, trainer) -> Any:
    """Preemption-transparent init: restore latest if present, else
    fresh init (the managed-jobs recovery contract).

    Only a *frozen-base finetune* config (`train_only` set) is allowed
    to fall back to the params-only partial restore when the exact tree
    does not match — opening a base checkpoint with a LoRA config is
    the intended use.  A normal resume that fails to restore raises:
    silently restarting from step 0 (and then garbage-collecting the
    real checkpoints) would be data loss.
    """
    state = trainer.init_state()
    try:
        restored = restore(manager, state)
    except Exception as e:  # noqa: BLE001 — orbax raises various types
        if manager.latest_step() is None or \
                not getattr(trainer.config, 'train_only', None):
            raise
        logger.info(f'Exact-tree restore failed ({type(e).__name__}) '
                    'and train_only is set: attempting params-only '
                    'partial restore of the base checkpoint.')
        restored = restore_params_partial(manager, state)
    if restored is not None:
        trainer.state = restored
        return restored
    return state
