"""Immutable Resources model with TPU pod slices as the first-class unit.

Counterpart of the reference's sky/resources.py:31-1631, redesigned so that
TPU topology is structural rather than a GCP special case: an accelerator
like `tpu-v5p-128` resolves to a `TpuSliceSpec` that the optimizer,
provisioner and gang launcher all consume (`num_hosts`, chips/host, ICI
topology).  Key reference behaviors preserved:
  - validation pipeline before any cloud call (resources.py:750-1016)
  - `less_demanding_than` for cluster-reuse fit checks (resources.py:1119)
  - `need_cleanup_after_preemption_or_failure` — preempted TPU VMs must be
    *deleted*, not stopped (resources.py:633)
  - `copy(**override)` returning a new frozen instance
  - YAML round-trip incl. `any_of:` / `ordered:` candidate sets.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Set, Union

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.utils import accelerator_registry
from skypilot_tpu.utils import schemas

logger = sky_logging.init_logger(__name__)

_DEFAULT_DISK_SIZE_GB = 256


class Resources:
    """A (possibly partial) specification of compute resources.

    Unset fields mean "let the optimizer decide".  Instances are immutable;
    use `.copy(**overrides)`.
    """

    _VERSION = 1

    def __init__(
        self,
        cloud: Optional[Union[str, 'clouds.Cloud']] = None,
        instance_type: Optional[str] = None,
        cpus: Optional[Union[int, float, str]] = None,
        memory: Optional[Union[int, float, str]] = None,
        accelerators: Optional[Union[str, Dict[str, int]]] = None,
        accelerator_args: Optional[Dict[str, Any]] = None,
        use_spot: Optional[bool] = None,
        job_recovery: Optional[Union[str, Dict[str, Any]]] = None,
        region: Optional[str] = None,
        zone: Optional[str] = None,
        disk_size: Optional[int] = None,
        disk_tier: Optional[str] = None,
        ports: Optional[Union[int, str, List[Union[int, str]]]] = None,
        labels: Optional[Dict[str, str]] = None,
        image_id: Optional[str] = None,
        _cluster_config_overrides: Optional[Dict[str, Any]] = None,
    ) -> None:
        from skypilot_tpu import clouds  # deferred: avoid import cycle
        self._cloud: Optional['clouds.Cloud'] = None
        if cloud is not None:
            if isinstance(cloud, str):
                self._cloud = clouds.CLOUD_REGISTRY.from_str(cloud)
            else:
                self._cloud = cloud
        self._instance_type = instance_type
        self._cpus = str(cpus) if cpus is not None else None
        self._memory = str(memory) if memory is not None else None
        self._accelerators = self._canonicalize_accelerators(accelerators)
        self._accelerator_args = dict(accelerator_args or {}) or None
        self._use_spot_specified = use_spot is not None
        self._use_spot = bool(use_spot) if use_spot is not None else False
        self._job_recovery = self._parse_job_recovery(job_recovery)
        self._region = region
        self._zone = zone
        self._disk_size = (int(disk_size)
                           if disk_size is not None else _DEFAULT_DISK_SIZE_GB)
        self._disk_size_specified = disk_size is not None
        self._disk_tier = disk_tier
        self._ports = self._parse_ports(ports)
        self._labels = dict(labels) if labels else None
        self._image_id = image_id
        self._cluster_config_overrides = _cluster_config_overrides or {}

        self._tpu_slice: Optional[accelerator_registry.TpuSliceSpec] = None
        if self._accelerators is not None:
            for name, count in self._accelerators.items():
                if accelerator_registry.is_tpu({name: count}):
                    self._tpu_slice = accelerator_registry.parse_tpu_accelerator(
                        name, count)
        self._validate()

    # -- parsing helpers ---------------------------------------------------
    @staticmethod
    def _canonicalize_accelerators(
        accelerators: Optional[Union[str, Dict[str, int]]]
    ) -> Optional[Dict[str, int]]:
        if accelerators is None:
            return None
        if isinstance(accelerators, str):
            if ':' in accelerators:
                name, count_s = accelerators.split(':', 1)
                try:
                    count = int(count_s)
                except ValueError:
                    raise exceptions.ResourcesValidationError(
                        f'Invalid accelerator count in {accelerators!r}.')
            else:
                name, count = accelerators, 1
            accelerators = {name: count}
        if len(accelerators) != 1:
            raise exceptions.ResourcesValidationError(
                f'Only one accelerator type per task is supported, got '
                f'{accelerators}.')
        out: Dict[str, int] = {}
        for name, count in accelerators.items():
            if name.lower().startswith('tpu-'):
                spec = accelerator_registry.parse_tpu_accelerator(
                    name, int(count))
                # Normalize to name-embedded-count form with count 1:
                # accelerators={'tpu-v5p-128': 1}.
                out[spec.accelerator_name] = 1
            else:
                canonical = accelerator_registry.canonicalize_accelerator_name(
                    name)
                out[canonical] = int(count)
        return out

    @staticmethod
    def _parse_job_recovery(
        job_recovery: Optional[Union[str, Dict[str, Any]]]
    ) -> Optional[Dict[str, Any]]:
        """Normalize `job_recovery: EAGER_NEXT_REGION` or
        `{strategy:..., max_restarts_on_errors: N}` (reference
        resources.py:439)."""
        if job_recovery is None:
            return None
        if isinstance(job_recovery, str):
            return {'strategy': job_recovery.upper()}
        out = dict(job_recovery)
        if 'strategy' in out and isinstance(out['strategy'], str):
            out['strategy'] = out['strategy'].upper()
        return out

    @staticmethod
    def _parse_ports(
        ports: Optional[Union[int, str, List[Union[int, str]]]]
    ) -> Optional[List[str]]:
        if ports is None:
            return None
        if isinstance(ports, (int, str)):
            ports = [ports]
        out = []
        for p in ports:
            s = str(p)
            if '-' in s:
                lo, hi = s.split('-', 1)
                lo_i, hi_i = int(lo), int(hi)
                if not 1 <= lo_i <= hi_i <= 65535:
                    raise exceptions.ResourcesValidationError(
                        f'Invalid port range {s!r}.')
            else:
                if not 1 <= int(s) <= 65535:
                    raise exceptions.ResourcesValidationError(
                        f'Invalid port {s!r}.')
            out.append(s)
        return sorted(set(out)) or None

    # -- validation pipeline ----------------------------------------------
    def _validate(self) -> None:
        self._try_validate_cpus_memory()
        self._try_validate_tpu()
        self._try_validate_region_zone()
        self._try_validate_disk_tier()
        self._try_validate_instance_type()

    def _try_validate_cpus_memory(self) -> None:
        for label, value in (('cpus', self._cpus), ('memory', self._memory)):
            if value is None:
                continue
            s = value[:-1] if value.endswith(('+', 'x')) else value
            try:
                v = float(s)
            except ValueError:
                raise exceptions.ResourcesValidationError(
                    f'Invalid {label} spec {value!r}: expected a number with '
                    "optional '+' suffix (e.g. '8', '8+').")
            if v <= 0:
                raise exceptions.ResourcesValidationError(
                    f'{label} must be positive, got {value!r}.')

    def _try_validate_tpu(self) -> None:
        if self._tpu_slice is None:
            if self._accelerator_args:
                tpu_only_keys = {'runtime_version', 'tpu_name', 'tpu_vm',
                                 'topology', 'provision_mode',
                                 'reservation'}
                bad = set(self._accelerator_args) & tpu_only_keys
                if bad:
                    raise exceptions.ResourcesValidationError(
                        f'accelerator_args {sorted(bad)} are only valid for '
                        'TPU accelerators.')
            return
        args = dict(self._accelerator_args or {})
        if not args.get('tpu_vm', True):
            raise exceptions.ResourcesValidationError(
                'Legacy TPU Node architecture is not supported; only TPU VM '
                '(the reference deprecates TPU nodes as well, '
                'sky/clouds/gcp.py:193-204).')
        mode = args.get('provision_mode', 'direct')
        if mode not in ('direct', 'queued'):
            raise exceptions.ResourcesValidationError(
                f"provision_mode must be 'direct' or 'queued', got "
                f'{mode!r}.')
        if args.get('reservation') and self._use_spot:
            raise exceptions.ResourcesValidationError(
                'use_spot and reservation are mutually exclusive.')
        args.setdefault('runtime_version',
                        self._tpu_slice.default_runtime_version())
        self._accelerator_args = args
        if self._use_spot and self._tpu_slice.generation.name == 'v2':
            logger.debug('v2 spot availability is limited.')

    def _try_validate_region_zone(self) -> None:
        if self._zone is not None and self._region is None:
            # Infer region from zone (e.g. us-central2-b -> us-central2).
            parts = self._zone.rsplit('-', 1)
            if len(parts) == 2 and len(parts[1]) <= 2:
                self._region = parts[0]
        if self._cloud is not None and self._region is not None:
            valid = self._cloud.validate_region_zone(self._region, self._zone)
            if not valid:
                raise exceptions.ResourcesValidationError(
                    f'Invalid region/zone {self._region}/{self._zone} for '
                    f'cloud {self._cloud}.')

    def _try_validate_disk_tier(self) -> None:
        if self._disk_tier is not None and self._disk_tier not in (
                'low', 'medium', 'high', 'ultra', 'best'):
            raise exceptions.ResourcesValidationError(
                f'Invalid disk_tier {self._disk_tier!r}; expected one of '
                "'low', 'medium', 'high', 'ultra', 'best'.")

    def _try_validate_instance_type(self) -> None:
        if self._instance_type is None or self._cloud is not None:
            return
        from skypilot_tpu import clouds
        feasible = [
            cloud for cloud in clouds.CLOUD_REGISTRY.values()
            if cloud.instance_type_exists(self._instance_type)
        ]
        if len(feasible) == 1:
            self._cloud = feasible[0]
        elif len(feasible) > 1:
            raise exceptions.ResourcesValidationError(
                f'Instance type {self._instance_type!r} exists in multiple '
                f'clouds {feasible}; please specify `cloud`.')

    # -- accessors ---------------------------------------------------------
    @property
    def cloud(self):
        return self._cloud

    @property
    def instance_type(self) -> Optional[str]:
        return self._instance_type

    @property
    def cpus(self) -> Optional[str]:
        return self._cpus

    @property
    def memory(self) -> Optional[str]:
        return self._memory

    @property
    def accelerators(self) -> Optional[Dict[str, int]]:
        return dict(self._accelerators) if self._accelerators else None

    @property
    def accelerator_args(self) -> Optional[Dict[str, Any]]:
        return dict(self._accelerator_args) if self._accelerator_args else None

    @property
    def tpu_slice(self) -> Optional[accelerator_registry.TpuSliceSpec]:
        return self._tpu_slice

    @property
    def is_tpu(self) -> bool:
        return self._tpu_slice is not None

    @property
    def num_hosts_per_node(self) -> int:
        """Hosts behind one logical node. >1 for TPU pod slices (the
        reference's num_ips_per_node, cloud_vm_ray_backend.py:2550)."""
        if self._tpu_slice is not None:
            return self._tpu_slice.num_hosts
        return 1

    @property
    def use_spot(self) -> bool:
        return self._use_spot

    @property
    def use_spot_specified(self) -> bool:
        return self._use_spot_specified

    @property
    def job_recovery(self) -> Optional[Dict[str, Any]]:
        return self._job_recovery

    @property
    def region(self) -> Optional[str]:
        return self._region

    @property
    def zone(self) -> Optional[str]:
        return self._zone

    @property
    def disk_size(self) -> int:
        return self._disk_size

    @property
    def disk_tier(self) -> Optional[str]:
        return self._disk_tier

    @property
    def ports(self) -> Optional[List[str]]:
        return list(self._ports) if self._ports else None

    @property
    def labels(self) -> Optional[Dict[str, str]]:
        return dict(self._labels) if self._labels else None

    @property
    def image_id(self) -> Optional[str]:
        return self._image_id

    @property
    def cluster_config_overrides(self) -> Dict[str, Any]:
        return dict(self._cluster_config_overrides)

    @property
    def need_cleanup_after_preemption_or_failure(self) -> bool:
        """Preempted/failed TPU VMs cannot be restarted in place — they must
        be deleted and re-created (reference: sky/resources.py:633, consumed
        by the jobs controller at sky/jobs/controller.py:352-360)."""
        return self.is_tpu

    def is_launchable(self) -> bool:
        return self._cloud is not None and self._instance_type is not None

    # -- cost --------------------------------------------------------------
    def get_cost(self, seconds: float) -> float:
        """Cost in $ for running this resource for `seconds`."""
        hours = seconds / 3600.0
        assert self._cloud is not None and self._instance_type is not None, (
            'get_cost() requires launchable resources.')
        cost = self._cloud.instance_type_to_hourly_cost(
            self._instance_type, self._use_spot, self._region, self._zone)
        if self._accelerators is not None:
            cost += self._cloud.accelerators_to_hourly_cost(
                self._accelerators, self._use_spot, self._region, self._zone)
        return cost * hours

    # -- deploy variables --------------------------------------------------
    def make_deploy_variables(self, cluster_name_on_cloud: str,
                              region: 'clouds.Region',
                              zones: Optional[List['clouds.Zone']],
                              num_nodes: int) -> Dict[str, Any]:
        assert self._cloud is not None
        return self._cloud.make_deploy_resources_variables(
            self, cluster_name_on_cloud, region, zones, num_nodes)

    # -- comparison --------------------------------------------------------
    def less_demanding_than(self, other: 'Resources',
                            requested_num_nodes: int = 1) -> bool:
        """True if `self` fits on a cluster provisioned as `other`.

        Used for cluster-reuse checks on `exec`/relaunch (reference
        sky/resources.py:1119).
        """
        if self._cloud is not None and not self._cloud.is_same_cloud(
                other.cloud):
            return False
        if self._region is not None and self._region != other.region:
            return False
        if self._zone is not None and self._zone != other.zone:
            return False
        if (self._instance_type is not None and
                self._instance_type != other.instance_type):
            return False
        if self._use_spot_specified and self._use_spot != other.use_spot:
            return False
        if self._accelerators is not None:
            if other.accelerators is None:
                return False
            for name, count in self._accelerators.items():
                if other.accelerators.get(name, 0) < count:
                    return False
        if self._ports is not None:
            other_ports = set(other.ports or [])
            if not set(self._ports) <= other_ports:
                return False
        return True

    # -- copy / serialization ---------------------------------------------
    def copy(self, **override: Any) -> 'Resources':
        fields = dict(
            cloud=self._cloud,
            instance_type=self._instance_type,
            cpus=self._cpus,
            memory=self._memory,
            accelerators=self.accelerators,
            accelerator_args=self.accelerator_args,
            use_spot=self._use_spot if self._use_spot_specified else None,
            job_recovery=self._job_recovery,
            region=self._region,
            zone=self._zone,
            disk_size=(self._disk_size
                       if self._disk_size_specified else None),
            disk_tier=self._disk_tier,
            ports=self.ports,
            labels=self.labels,
            image_id=self._image_id,
            _cluster_config_overrides=self._cluster_config_overrides,
        )
        fields.update(override)
        return Resources(**fields)

    @classmethod
    def from_yaml_config(
        cls, config: Optional[Dict[str, Any]]
    ) -> Union['Resources', List['Resources'], Set['Resources']]:
        """Build Resources (or an any_of set / ordered list) from YAML.

        Reference: sky/resources.py from_yaml_config with any_of/ordered
        candidate-resources support.
        """
        if config is None:
            return Resources()
        schemas.validate(config, schemas.get_resources_schema(),
                         exceptions.ResourcesValidationError,
                         'Invalid resources: ')
        config = dict(config)
        any_of = config.pop('any_of', None)
        ordered = config.pop('ordered', None)
        if any_of is not None and ordered is not None:
            raise exceptions.ResourcesValidationError(
                'Cannot specify both any_of and ordered.')

        def _build(override: Dict[str, Any]) -> 'Resources':
            merged = {**config, **override}
            return cls(**merged)  # type: ignore[arg-type]

        if any_of is not None:
            return {_build(o or {}) for o in any_of}
        if ordered is not None:
            return [_build(o or {}) for o in ordered]
        return _build({})

    def to_yaml_config(self) -> Dict[str, Any]:
        config: Dict[str, Any] = {}

        def add(key: str, value: Any) -> None:
            if value is not None:
                config[key] = value

        add('cloud', str(self._cloud) if self._cloud else None)
        add('instance_type', self._instance_type)
        add('cpus', self._cpus)
        add('memory', self._memory)
        if self._accelerators:
            add('accelerators', self._accelerators)
        add('accelerator_args', self.accelerator_args)
        if self._use_spot_specified:
            add('use_spot', self._use_spot)
        add('job_recovery', self._job_recovery)
        add('region', self._region)
        add('zone', self._zone)
        if self._disk_size_specified:
            add('disk_size', self._disk_size)
        add('disk_tier', self._disk_tier)
        add('ports', self.ports)
        add('labels', self.labels)
        add('image_id', self._image_id)
        return config

    # -- dunder ------------------------------------------------------------
    def __eq__(self, other: Any) -> bool:
        if not isinstance(other, Resources):
            return NotImplemented
        return self.to_yaml_config() == other.to_yaml_config()

    def __hash__(self) -> int:
        def freeze(v):
            if isinstance(v, dict):
                return tuple(sorted((k, freeze(x)) for k, x in v.items()))
            if isinstance(v, list):
                return tuple(freeze(x) for x in v)
            return v

        return hash(freeze(self.to_yaml_config()))

    def __repr__(self) -> str:
        parts = []
        if self._cloud is not None:
            parts.append(str(self._cloud))
        if self._instance_type is not None:
            parts.append(self._instance_type)
        if self._accelerators is not None:
            accs = ', '.join(f'{k}:{v}' if v != 1 else k
                             for k, v in self._accelerators.items())
            parts.append(f'{{{accs}}}')
            if self._tpu_slice is not None and self._tpu_slice.is_pod:
                parts.append(f'[{self._tpu_slice.num_hosts} hosts]')
        if self._cpus is not None:
            parts.append(f'cpus={self._cpus}')
        if self._memory is not None:
            parts.append(f'mem={self._memory}')
        if self._use_spot:
            parts.append('[Spot]')
        if self._region is not None:
            parts.append(f'region={self._region}')
        if self._zone is not None:
            parts.append(f'zone={self._zone}')
        inner = ', '.join(parts) if parts else ''
        return f'Resources({inner})'
