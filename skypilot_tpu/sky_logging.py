"""Logging setup.

Env-tunable logger analogous to the reference's sky/sky_logging.py:1-179:
a single stream handler with an optional rich-style prefix, module-level
`init_logger`, and context managers to silence output in nested calls
(used when controllers invoke the SDK recursively).
"""
from __future__ import annotations

import contextlib
import logging
import os
import sys
import threading

_FORMAT = '%(levelname).1s %(asctime)s %(filename)s:%(lineno)d] %(message)s'
_DATE_FORMAT = '%m-%d %H:%M:%S'

_root_logger = logging.getLogger('skypilot_tpu')
_default_handler = None
_lock = threading.Lock()


def _setup() -> None:
    global _default_handler
    with _lock:
        if _default_handler is not None:
            return
        _default_handler = logging.StreamHandler(sys.stdout)
        _default_handler.flush = sys.stdout.flush  # type: ignore[method-assign]
        level = os.environ.get('SKYTPU_DEBUG')
        _default_handler.setLevel(
            logging.DEBUG if level == '1' else logging.INFO)
        _default_handler.setFormatter(
            logging.Formatter(_FORMAT, datefmt=_DATE_FORMAT))
        _root_logger.addHandler(_default_handler)
        _root_logger.setLevel(logging.DEBUG)
        _root_logger.propagate = False


def init_logger(name: str) -> logging.Logger:
    _setup()
    return logging.getLogger(name if name.startswith('skypilot_tpu')
                             else f'skypilot_tpu.{name}')


@contextlib.contextmanager
def silent():
    """Suppress all framework log output inside the context.

    Used when the SDK is invoked programmatically by controllers
    (reference: sky/sky_logging.py silent()).
    """
    _setup()
    assert _default_handler is not None
    previous = _default_handler.level
    _default_handler.setLevel(logging.CRITICAL)
    try:
        yield
    finally:
        _default_handler.setLevel(previous)


def is_silent() -> bool:
    _setup()
    assert _default_handler is not None
    return _default_handler.level >= logging.CRITICAL


def set_verbose(verbose: bool) -> None:
    _setup()
    assert _default_handler is not None
    _default_handler.setLevel(logging.DEBUG if verbose else logging.INFO)
