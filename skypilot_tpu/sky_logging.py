"""Logging setup.

Env-tunable logger analogous to the reference's sky/sky_logging.py:1-179:
a single stream handler with an optional rich-style prefix, module-level
`init_logger`, and context managers to silence output in nested calls
(used when controllers invoke the SDK recursively).

Set ``SKYTPU_LOG_JSON=1`` to emit one JSON object per line
(``{"ts", "level", "logger", "msg"}``) on the same handler, so framework
logs can be machine-ingested alongside the bench JSON line and the
trace JSONL sink.
"""
from __future__ import annotations

import contextlib
import json
import logging
import os
import sys
import threading

_FORMAT = '%(levelname).1s %(asctime)s %(filename)s:%(lineno)d] %(message)s'
_DATE_FORMAT = '%m-%d %H:%M:%S'

_root_logger = logging.getLogger('skypilot_tpu')
_default_handler = None
_lock = threading.Lock()


class JsonFormatter(logging.Formatter):
    """One JSON object per line: ts (unix seconds), level, logger, msg."""

    def format(self, record: logging.LogRecord) -> str:
        payload = {
            'ts': round(record.created, 6),
            'level': record.levelname,
            'logger': record.name,
            'msg': record.getMessage(),
        }
        if record.exc_info and record.exc_info[0] is not None:
            payload['exc'] = self.formatException(record.exc_info)
        return json.dumps(payload, default=str)


def make_formatter() -> logging.Formatter:
    """The formatter the shared handler should use (env-dependent)."""
    if os.environ.get('SKYTPU_LOG_JSON') == '1':
        return JsonFormatter()
    return logging.Formatter(_FORMAT, datefmt=_DATE_FORMAT)


def _setup() -> None:
    global _default_handler
    with _lock:
        if _default_handler is not None:
            return
        _default_handler = logging.StreamHandler(sys.stdout)
        _default_handler.flush = sys.stdout.flush  # type: ignore[method-assign]
        level = os.environ.get('SKYTPU_DEBUG')
        _default_handler.setLevel(
            logging.DEBUG if level == '1' else logging.INFO)
        _default_handler.setFormatter(make_formatter())
        _root_logger.addHandler(_default_handler)
        _root_logger.setLevel(logging.DEBUG)
        _root_logger.propagate = False


def init_logger(name: str) -> logging.Logger:
    _setup()
    return logging.getLogger(name if name.startswith('skypilot_tpu')
                             else f'skypilot_tpu.{name}')


@contextlib.contextmanager
def silent():
    """Suppress all framework log output inside the context.

    Used when the SDK is invoked programmatically by controllers
    (reference: sky/sky_logging.py silent()).
    """
    _setup()
    assert _default_handler is not None
    previous = _default_handler.level
    _default_handler.setLevel(logging.CRITICAL)
    try:
        yield
    finally:
        _default_handler.setLevel(previous)


def is_silent() -> bool:
    _setup()
    assert _default_handler is not None
    return _default_handler.level >= logging.CRITICAL


def set_verbose(verbose: bool) -> None:
    _setup()
    assert _default_handler is not None
    _default_handler.setLevel(logging.DEBUG if verbose else logging.INFO)
