"""Fused flash attention for TPU (Pallas) with a memory-efficient VJP.

The reference delegates attention to torch-xla's flash attention
(docs/source/reference/tpu.rst:99-127 `torch_xla[pallas]` +
`--flash_attention`); here it is a first-party kernel:

  - forward: online-softmax flash attention (Dao et al.) as a Pallas TPU
    kernel — grid (batch*heads, q_blocks, kv_blocks) with kv innermost,
    f32 accumulators in VMEM scratch, causal blocks skipped entirely
    (upper-triangular tiles never touch the MXU);
  - backward: FlashAttention-2 as two Pallas kernels sharing the saved
    logsumexp and delta=rowsum(dO*O): a dq pass (kv blocks innermost)
    and a dk/dv pass (q blocks innermost), both with f32 VMEM
    accumulators and causal blocks skipped; off-TPU default falls back
    to a blockwise jnp double-scan that XLA fuses fine on CPU;
  - off-TPU with SKYTPU_FORCE_PALLAS=1 (tests) the same kernels run in
    interpreter mode.

Layout: [batch, num_heads, seq, head_dim] ("BHSD"), head_dim a multiple
of 128 on TPU for MXU alignment.  K/V may carry fewer heads than q
(GQA/MQA) — they are read unbroadcast; see `flash_attention`.
"""
from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
from jax.ad_checkpoint import checkpoint_name
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_KV = 512
_NEG_INF = -1e30


def _on_tpu() -> bool:
    return jax.default_backend() == 'tpu'


# Tests pin the pallas kernel (interpret mode) off-TPU; everything else
# off-TPU uses the XLA-native forward — interpret mode is orders of
# magnitude slower and its HLO interpreter rejects mixed varying-manual
# -axes operands inside partial-manual shard_map regions.
FORCE_PALLAS = os.environ.get('SKYTPU_FORCE_PALLAS', '') == '1'


def _group_counts(q: jax.Array, k: jax.Array) -> Tuple[int, int]:
    """(kv_heads, group) for GQA inputs; validates divisibility."""
    heads, kvh = q.shape[1], k.shape[1]
    if heads % kvh:
        raise ValueError(
            f'query heads ({heads}) not divisible by kv heads ({kvh})')
    return kvh, heads // kvh


def _mha_fwd_xla(q: jax.Array, k: jax.Array, v: jax.Array, *,
                 scale: float, causal: bool,
                 window: Optional[int] = None,
                 offset: int = 0
                 ) -> Tuple[jax.Array, jax.Array]:
    """XLA-native (out, lse) forward with the same semantics as the
    pallas kernel (used off-TPU; XLA fuses this fine on CPU).

    GQA inputs (k/v with fewer heads than q) contract grouped —
    [B, kvh, G, Sq, d] x [B, kvh, Sk, d] — so K/V are never broadcast
    to H heads in HBM; with kvh == H the group axis is size 1 and the
    math is the classic per-head form.

    `offset`: query block's global position lead over the kv block
    (ring attention off-diagonal pairs): query row r sits at global
    position r + offset relative to kv column positions."""
    batch, heads, seq_q, _ = q.shape
    kvh, group = _group_counts(q, k)
    qg = q.astype(jnp.float32).reshape(batch, kvh, group, seq_q,
                                       q.shape[-1])
    s = jnp.einsum('bngqd,bnkd->bngqk', qg,
                   k.astype(jnp.float32)) * scale
    if causal:
        seq_kv = k.shape[2]
        mask = jnp.tril(jnp.ones((seq_q, seq_kv), bool),
                        k=seq_kv - seq_q + offset)
        if window is not None:
            # Sliding window: each query attends to its last `window`
            # positions (inclusive of itself).
            mask &= ~jnp.tril(jnp.ones((seq_q, seq_kv), bool),
                              k=seq_kv - seq_q + offset - window)
        s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = jnp.einsum('bngqk,bnkd->bngqd', p / l_safe,
                     v.astype(jnp.float32)).astype(q.dtype)
    out = out.reshape(batch, heads, seq_q, v.shape[-1])
    lse = (m + jnp.log(l_safe))[..., 0].reshape(batch, heads, seq_q)
    return out, lse


def _out_vma(*arrays):
    """Varying-manual-axes type for pallas outputs: the union of the
    inputs' vma (empty outside shard_map; e.g. {'pipe'} inside a
    pipeline stage, {'context'} inside a ring-attention shard).

    None on jax builds without `jax.typeof` (pre-vma-typing): there the
    manual-axes machinery doesn't exist, so outputs carry no vma."""
    typeof = getattr(jax, 'typeof', None)
    if typeof is None:
        return None
    vmas = [getattr(typeof(a), 'vma', None) for a in arrays]
    vmas = [v for v in vmas if v is not None]
    if not vmas:
        return None
    return frozenset().union(*vmas)


def _sds(shape, dtype, vma):
    """ShapeDtypeStruct that only passes `vma=` when there is one —
    older jax's constructor rejects the kwarg outright."""
    if vma is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)


def _cast_vma(x: jax.Array, vma) -> jax.Array:
    """Mark a freshly-created (replicated-typed) array as varying over
    `vma` so scan carries type-check inside shard_map manual regions."""
    typeof = getattr(jax, 'typeof', None)
    if typeof is None:
        return x
    have = getattr(typeof(x), 'vma', None) or frozenset()
    missing = (vma or frozenset()) - have
    if missing:
        return jax.lax.pcast(x, tuple(missing), to='varying')
    return x


def _pick_block(seq: int, requested: int, what: str) -> int:
    """Largest block <= requested that exactly divides seq.

    Sequences must be a multiple of 128 (TPU lane width); partial edge
    blocks would otherwise pollute the non-causal softmax (forward pads)
    and break the blockwise backward reshape.
    """
    if seq % 128 != 0 and seq < 128:
        # Tiny sequences (tests): one block covering everything.
        return seq
    if seq % 128 != 0:
        raise ValueError(
            f'flash_attention requires {what} length divisible by 128, '
            f'got {seq}. Pad the sequence.')
    b = min(requested, seq)
    b -= b % 128
    while b > 0 and seq % b != 0:
        b -= 128
    return max(b, 128)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------
def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                      acc_ref, m_ref, l_ref, *, scale: float,
                      causal: bool, window: Optional[int],
                      offset: int, block_q: int,
                      block_kv: int) -> None:
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = qi * block_q
    k_start = ki * block_kv
    # Causal: a kv block strictly above the diagonal contributes nothing.
    # Window: a kv block entirely below every query's window start is
    # skipped too — this is where sliding-window attention goes from
    # O(S^2) to O(S*W) compute.
    should_run = True
    if causal:
        should_run = k_start <= q_start + offset + block_q - 1
        if window is not None:
            should_run &= \
                k_start + block_kv - 1 >= q_start + offset - window + 1

    @pl.when(should_run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)           # [bq, d]
        k = k_ref[0].astype(jnp.float32)           # [bkv, d]
        v = v_ref[0].astype(jnp.float32)           # [bkv, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bkv]
        if causal:
            rows = q_start + offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0)
            cols = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1)
            keep = rows >= cols
            if window is not None:
                keep &= cols >= rows - window + 1
            s = jnp.where(keep, s, _NEG_INF)
        m_prev = m_ref[:, :1]                       # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)   # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                      # [bq, bkv]
        correction = jnp.exp(m_prev - m_new)        # [bq, 1]
        l_new = correction * l_ref[:, :1] + \
            jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * correction + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        lse = m_ref[:, :1] + jnp.log(l_safe)
        lse_ref[0] = lse.astype(lse_ref.dtype)


def _flash_fwd(q: jax.Array, k: jax.Array, v: jax.Array, *, scale: float,
               causal: bool, window: Optional[int], offset: int,
               block_q: int,
               block_kv: int) -> Tuple[jax.Array, jax.Array]:
    batch, heads, seq_q, d = q.shape
    seq_kv = k.shape[2]
    kvh, group = _group_counts(q, k)
    bh = batch * heads
    block_q = _pick_block(seq_q, block_q, 'query')
    block_kv = _pick_block(seq_kv, block_kv, 'key/value')
    q3 = q.reshape(bh, seq_q, d)
    k3 = k.reshape(batch * kvh, seq_kv, d)
    v3 = v.reshape(batch * kvh, seq_kv, d)
    grid = (bh, pl.cdiv(seq_q, block_q), pl.cdiv(seq_kv, block_kv))
    kernel = functools.partial(_flash_fwd_kernel, scale=scale,
                               causal=causal, window=window,
                               offset=offset, block_q=block_q,
                               block_kv=block_kv)
    # GQA without materialization: program b serves query head
    # (b % heads); its kv row in the UNBROADCAST k3/v3 is the group's
    # shared head — the index map aliases group members onto the same
    # block, so the broadcast happens in the BlockSpec, not in HBM.
    kv_row = lambda b: b // heads * kvh + (b % heads) // group
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_kv, d),
                         lambda b, i, j: (kv_row(b), j, 0)),
            pl.BlockSpec((1, block_kv, d),
                         lambda b, i, j: (kv_row(b), j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            # lse as [bh, seq, 1]: TPU block tiling needs the last two
            # dims (8,128)-divisible or equal to the array dims.
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            _sds((bh, seq_q, d), q.dtype, _out_vma(q3, k3, v3)),
            _sds((bh, seq_q, 1), jnp.float32, _out_vma(q3, k3, v3)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=not _on_tpu(),
    )(q3, k3, v3)
    return (out.reshape(batch, heads, seq_q, d),
            lse.reshape(batch, heads, seq_q))  # lse [bh,seq,1] squeezed


# ---------------------------------------------------------------------------
# backward kernels (FlashAttention-2, two-pass: dq then dk/dv)
# ---------------------------------------------------------------------------
def _bwd_block_math(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    q_start, k_start, *, scale: float, causal: bool,
                    window: Optional[int], offset: int, block_q: int,
                    block_kv: int):
    """Shared FA2 recompute for one (q, kv) block pair.

    Returns (q, k, do, p, ds) in f32 — everything the dq and dk/dv
    kernels need for their respective accumulation matmuls.  Kept as
    one helper so the mask/scale math can never desynchronize between
    the two backward passes.
    """
    q = q_ref[0].astype(jnp.float32)            # [bq, d]
    k = k_ref[0].astype(jnp.float32)            # [bkv, d]
    v = v_ref[0].astype(jnp.float32)            # [bkv, d]
    do = do_ref[0].astype(jnp.float32)          # [bq, d]
    lse = lse_ref[0]                            # [bq, 1] f32
    delta = delta_ref[0]                        # [bq, 1] f32
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale   # [bq, bkv]
    if causal:
        rows = q_start + offset + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 0)
        cols = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 1)
        keep = rows >= cols
        if window is not None:
            keep &= cols >= rows - window + 1
        s = jnp.where(keep, s, _NEG_INF)
    p = jnp.exp(s - lse)                        # [bq, bkv]
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)     # [bq, bkv]
    ds = p * (dp - delta) * scale
    return q, k, do, p, ds


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, acc_ref, *, scale: float, causal: bool,
                         window: Optional[int], offset: int,
                         block_q: int, block_kv: int) -> None:
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start = qi * block_q
    k_start = ki * block_kv
    should_run = True
    if causal:
        # kv blocks strictly above the diagonal contribute nothing;
        # with a window, blocks entirely below it neither.
        should_run = k_start <= q_start + offset + block_q - 1
        if window is not None:
            should_run &= \
                k_start + block_kv - 1 >= q_start + offset - window + 1

    @pl.when(should_run)
    def _compute():
        _, k, _, _, ds = _bwd_block_math(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, q_start,
            k_start, scale=scale, causal=causal, window=window,
            offset=offset, block_q=block_q, block_kv=block_kv)
        acc_ref[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)     # [bq, d]

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_acc, dv_acc, *, scale: float,
                          causal: bool, window: Optional[int],
                          offset: int, block_q: int,
                          block_kv: int, nq_blocks: int) -> None:
    # Grid dim 0 runs over batch*KV heads; the inner dim folds (group
    # member, q block) as j = g * nq_blocks + qj so one kv block's
    # dk/dv accumulate over EVERY query head sharing it before the
    # output block flushes (init at the first inner step, finalize at
    # the last — accumulation across group members included).
    ki = pl.program_id(1)
    qj = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qj == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = (qj % nq_blocks) * block_q
    k_start = ki * block_kv
    should_run = True
    if causal:
        should_run = q_start + offset + block_q - 1 >= k_start
        if window is not None:
            should_run &= \
                k_start + block_kv - 1 >= q_start + offset - window + 1

    @pl.when(should_run)
    def _compute():
        q, _, do, p, ds = _bwd_block_math(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, q_start,
            k_start, scale=scale, causal=causal, window=window,
            offset=offset, block_q=block_q, block_kv=block_kv)
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)     # [bkv, d]
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)     # [bkv, d]

    @pl.when(qj == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_pallas(q: jax.Array, k: jax.Array, v: jax.Array,
                      do: jax.Array, lse: jax.Array, delta: jax.Array, *,
                      scale: float, causal: bool,
                      window: Optional[int], offset: int, block_q: int,
                      block_kv: int
                      ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Pallas dq + dk/dv backward. lse/delta are [B,H,S] f32.

    GQA (k/v at kvh < H heads): dq reads shared kv blocks through the
    same index-map aliasing as the forward; the dk/dv pass folds
    (group member, q block) into its inner grid dim so each kv block's
    gradients accumulate over all H/kvh query heads sharing it — dk/dv
    come back at [B, kvh, S, d], no repeated operand anywhere."""
    batch, heads, seq_q, d = q.shape
    seq_kv = k.shape[2]
    kvh, group = _group_counts(q, k)
    bh = batch * heads
    block_q = _pick_block(seq_q, block_q, 'query')
    block_kv = _pick_block(seq_kv, block_kv, 'key/value')
    nq = pl.cdiv(seq_q, block_q)
    nk = pl.cdiv(seq_kv, block_kv)
    q3 = q.reshape(bh, seq_q, d)
    k3 = k.reshape(batch * kvh, seq_kv, d)
    v3 = v.reshape(batch * kvh, seq_kv, d)
    do3 = do.reshape(bh, seq_q, d)
    lse3 = lse.astype(jnp.float32).reshape(bh, seq_q, 1)
    delta3 = delta.astype(jnp.float32).reshape(bh, seq_q, 1)
    vma = _out_vma(q3, k3, v3, do3)
    kv_row = lambda b: b // heads * kvh + (b % heads) // group

    q_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    kv_q_inner = pl.BlockSpec((1, block_kv, d),
                              lambda b, i, j: (kv_row(b), j, 0))
    row_spec = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0))

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, scale=scale,
                          causal=causal, window=window, offset=offset,
                          block_q=block_q, block_kv=block_kv),
        grid=(bh, nq, nk),
        in_specs=[q_spec, kv_q_inner, kv_q_inner, q_spec, row_spec,
                  row_spec],
        out_specs=q_spec,
        out_shape=_sds((bh, seq_q, d), jnp.float32, vma),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=not _on_tpu(),
    )(q3, k3, v3, do3, lse3, delta3)

    # dk/dv pass: grid dim 0 over batch*kvh, kv blocks next, then the
    # folded (group member, q block) inner dim j = g * nq + qj.  The
    # q-row for program (b, i, j) is batch (b // kvh), query head
    # (b % kvh) * group + j // nq.  Output kv blocks stay resident
    # across the whole inner sweep, so accumulation over group members
    # is contiguous (Pallas revisiting rule).
    q_row = lambda b, j: b // kvh * heads + (b % kvh) * group + j // nq
    kv_spec = pl.BlockSpec((1, block_kv, d), lambda b, i, j: (b, i, 0))
    q_inner = pl.BlockSpec((1, block_q, d),
                           lambda b, i, j: (q_row(b, j), j % nq, 0))
    row_inner = pl.BlockSpec((1, block_q, 1),
                             lambda b, i, j: (q_row(b, j), j % nq, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, scale=scale,
                          causal=causal, window=window, offset=offset,
                          block_q=block_q, block_kv=block_kv,
                          nq_blocks=nq),
        grid=(batch * kvh, nk, group * nq),
        in_specs=[q_inner, kv_spec, kv_spec, q_inner, row_inner,
                  row_inner],
        out_specs=[kv_spec, kv_spec],
        out_shape=[
            _sds((batch * kvh, seq_kv, d), jnp.float32, vma),
            _sds((batch * kvh, seq_kv, d), jnp.float32, vma),
        ],
        scratch_shapes=[pltpu.VMEM((block_kv, d), jnp.float32),
                        pltpu.VMEM((block_kv, d), jnp.float32)],
        interpret=not _on_tpu(),
    )(q3, k3, v3, do3, lse3, delta3)
    return (dq.reshape(batch, heads, seq_q, d),
            dk.reshape(batch, kvh, seq_kv, d),
            dv.reshape(batch, kvh, seq_kv, d))


# ---------------------------------------------------------------------------
# backward (FlashAttention-2 blockwise double-scan, jnp — off-TPU path)
# ---------------------------------------------------------------------------
def _flash_bwd_xla(q, k, v, do, lse, delta, *, scale: float, causal: bool,
                   window: Optional[int], offset: int,
                   block_q: int, block_kv: int
                   ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Grouped throughout: q/do blocks carry a [kvh, group] head split,
    k/v blocks stay at kvh heads, and the dk/dv einsums reduce over the
    group axis — so dk/dv come back at [B, kvh, S, d] (matching the
    unbroadcast inputs) without a repeated operand.  With kvh == H the
    group axis is size 1 and this is the classic per-head backward."""
    batch, heads, seq_q, d = q.shape
    seq_kv = k.shape[2]
    kvh, group = _group_counts(q, k)
    block_q = _pick_block(seq_q, block_q, 'query')
    block_kv = _pick_block(seq_kv, block_kv, 'key/value')
    nq = seq_q // block_q
    nk = seq_kv // block_kv
    vma = _out_vma(q, k, v, do)
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dof = do.astype(jnp.float32)

    q_blocks = qf.reshape(batch, kvh, group, nq, block_q, d)
    do_blocks = dof.reshape(batch, kvh, group, nq, block_q, d)
    lse_blocks = lse.reshape(batch, kvh, group, nq, block_q)
    delta_blocks = delta.reshape(batch, kvh, group, nq, block_q)
    k_blocks = kf.reshape(batch, kvh, nk, block_kv, d)
    v_blocks = vf.reshape(batch, kvh, nk, block_kv, d)

    def q_step(carry, qi):
        dk_acc, dv_acc = carry
        q_i = q_blocks[:, :, :, qi]                # [B,n,g,bq,d]
        do_i = do_blocks[:, :, :, qi]
        lse_i = lse_blocks[:, :, :, qi]            # [B,n,g,bq]
        delta_i = delta_blocks[:, :, :, qi]

        def kv_step(dq_i, ki):
            k_j = k_blocks[:, :, ki]               # [B,n,bkv,d]
            v_j = v_blocks[:, :, ki]
            s = jnp.einsum('bngqd,bnkd->bngqk', q_i, k_j) * scale
            if causal:
                rows = qi * block_q + offset + \
                    jax.lax.broadcasted_iota(
                        jnp.int32, (block_q, block_kv), 0)
                cols = ki * block_kv + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_kv), 1)
                keep = rows >= cols
                if window is not None:
                    keep &= cols >= rows - window + 1
                s = jnp.where(keep, s, _NEG_INF)
            p = jnp.exp(s - lse_i[..., None])      # [B,n,g,bq,bkv]
            dp = jnp.einsum('bngqd,bnkd->bngqk', do_i, v_j)
            ds = p * (dp - delta_i[..., None]) * scale
            dq_i = dq_i + jnp.einsum('bngqk,bnkd->bngqd', ds, k_j)
            # dk/dv reduce over the group axis too: every query head
            # sharing the kv head contributes to its gradient.
            dk_j = jnp.einsum('bngqk,bngqd->bnkd', ds, q_i)
            dv_j = jnp.einsum('bngqk,bngqd->bnkd', p, do_i)
            return dq_i, (dk_j, dv_j)

        dq_i0 = _cast_vma(jnp.zeros_like(q_i), vma)
        dq_i, (dk_js, dv_js) = jax.lax.scan(kv_step, dq_i0,
                                            jnp.arange(nk))
        # dk_js: [nk,B,n,bkv,d] — accumulate into the carried full dk/dv.
        dk_acc = dk_acc + jnp.moveaxis(dk_js, 0, 2).reshape(
            batch, kvh, seq_kv, d)
        dv_acc = dv_acc + jnp.moveaxis(dv_js, 0, 2).reshape(
            batch, kvh, seq_kv, d)
        return (dk_acc, dv_acc), dq_i

    (dk, dv), dq_blocks = jax.lax.scan(
        q_step,
        (_cast_vma(jnp.zeros((batch, kvh, seq_kv, d), jnp.float32),
                   vma),
         _cast_vma(jnp.zeros((batch, kvh, seq_kv, d), jnp.float32),
                   vma)),
        jnp.arange(nq))
    # dq_blocks: [nq,B,n,g,bq,d] -> [B,n,g,nq,bq,d] -> [B,H,Sq,d].
    dq = jnp.moveaxis(dq_blocks, 0, 3).reshape(batch, heads, seq_q, d)
    return dq, dk, dv


def _pair_bwd(q, k, v, do, lse, delta, *, scale: float, causal: bool,
              window: Optional[int] = None, offset: int = 0,
              block_q: int = DEFAULT_BLOCK_Q,
              block_kv: int = DEFAULT_BLOCK_KV
              ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """f32 (dq, dk, dv) given saved lse and delta=rowsum(dO*O).

    Shared with ring attention, which calls it once per (q chunk,
    kv chunk) ring pair with the global lse/delta.
    """
    if not _on_tpu() and not FORCE_PALLAS:
        return _flash_bwd_xla(q, k, v, do, lse, delta, scale=scale,
                              causal=causal, window=window,
                              offset=offset, block_q=block_q,
                              block_kv=block_kv)
    return _flash_bwd_pallas(q, k, v, do, lse, delta, scale=scale,
                             causal=causal, window=window,
                             offset=offset, block_q=block_q,
                             block_kv=block_kv)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    scale: Optional[float] = None, causal: bool = True,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_kv: int = DEFAULT_BLOCK_KV,
                    window: Optional[int] = None) -> jax.Array:
    """Flash attention over [batch, heads, seq, head_dim] inputs.

    GQA: k/v may carry fewer heads than q (kvh dividing H).  They are
    consumed UNBROADCAST — the Pallas kernels alias group members onto
    shared kv blocks via BlockSpec index maps and the XLA fallback
    contracts grouped einsums — and dk/dv come back at kvh heads, so
    callers never `jnp.repeat` K/V before (or gradients after) this op.

    `window`: sliding-window attention (Mistral-style) — each query
    attends to its last `window` positions including itself.  Blocks
    wholly outside the band are skipped, so compute is O(S*W) rather
    than O(S^2)/2.  Requires causal=True and seq_q == seq_kv.
    """
    out, _ = _fwd_impl(q, k, v, scale, causal, block_q, block_kv,
                       window)
    return out


def _fwd_impl(q, k, v, scale, causal, block_q, block_kv, window=None,
              offset=0):
    if window is not None:
        if not causal:
            raise ValueError('window requires causal=True')
        if q.shape[2] != k.shape[2]:
            raise ValueError(
                'window requires seq_q == seq_kv '
                f'({q.shape[2]} vs {k.shape[2]}).')
        if offset == 0 and window >= q.shape[2]:
            window = None  # full attention; skip the extra masking
    actual_scale = scale if scale is not None else q.shape[-1] ** -0.5
    if not _on_tpu() and not FORCE_PALLAS:
        return _mha_fwd_xla(q, k, v, scale=actual_scale, causal=causal,
                            window=window, offset=offset)
    return _flash_fwd(q, k, v, scale=actual_scale, causal=causal,
                      window=window, offset=offset, block_q=block_q,
                      block_kv=block_kv)


def _vjp_fwd(q, k, v, scale, causal, block_q, block_kv, window=None):
    out, lse = _fwd_impl(q, k, v, scale, causal, block_q, block_kv,
                         window)
    # Named residuals: under jax.checkpoint with policy
    # save_only_these_names('attn_out', 'attn_lse') the backward reuses
    # them instead of re-running the forward kernel (q/k/v projections
    # are cheap linear recomputes; the O(s^2) kernel is not).
    out = checkpoint_name(out, 'attn_out')
    lse = checkpoint_name(lse, 'attn_lse')
    return out, (q, k, v, out, lse)


def _vjp_bwd(scale, causal, block_q, block_kv, window, residuals, g):
    q, k, v, out, lse = residuals
    if window is not None and window >= q.shape[2]:
        window = None  # mirror _fwd_impl's normalization
    actual_scale = scale if scale is not None else q.shape[-1] ** -0.5
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)
    dq, dk, dv = _pair_bwd(q, k, v, g, lse, delta, scale=actual_scale,
                           causal=causal, window=window,
                           block_q=block_q, block_kv=block_kv)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_vjp_fwd, _vjp_bwd)


def mha_reference(q: jax.Array, k: jax.Array, v: jax.Array,
                  scale: Optional[float] = None,
                  causal: bool = True,
                  window: Optional[int] = None,
                  offset: int = 0) -> jax.Array:
    """Plain-jnp attention for correctness tests.

    Accepts GQA inputs (k/v at kvh <= H heads) like the kernels do —
    contracted grouped, never repeated."""
    actual_scale = scale if scale is not None else q.shape[-1] ** -0.5
    batch, heads, seq_q, d = q.shape
    kvh, group = _group_counts(q, k)
    qg = q.astype(jnp.float32).reshape(batch, kvh, group, seq_q, d)
    s = jnp.einsum('bngqd,bnkd->bngqk', qg,
                   k.astype(jnp.float32)) * actual_scale
    if causal:
        seq_kv = k.shape[2]
        mask = jnp.tril(jnp.ones((seq_q, seq_kv), bool),
                        k=seq_kv - seq_q + offset)
        if window is not None:
            mask &= ~jnp.tril(jnp.ones((seq_q, seq_kv), bool),
                              k=seq_kv - seq_q + offset - window)
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum('bngqk,bnkd->bngqd', p, v.astype(jnp.float32))
    return out.reshape(batch, heads, seq_q,
                       v.shape[-1]).astype(q.dtype)
