"""Grouped-query attention einsums that never broadcast K/V to H heads.

Decode is bandwidth-bound: with GQA (kvh < H) the old cached-attention
epilogue `jnp.repeat`-ed keys/values up to H query heads before the
score matmul, materializing a [B, H, S, d] operand in HBM every layer
every step — an h/kvh-fold inflation of the per-step cache working set.
For DeepSeek's absorbed MLA decode (ONE latent head, H up to 128) that
silently undid the latent-cache bandwidth win.

The fix is free: reshape queries to [B, kvh, H/kvh, Sq, d] and contract
against the *unbroadcast* [B, kvh, Sk, d] cache, so the head-group
broadcast happens inside the einsum (a batched matmul with the group
folded into the row dim — XLA never materializes the repeated operand).
Numerics are bit-identical to repeat-then-matmul: each (query head,
position) dot product sums the same values in the same order.

Shared by every family's decode path (llama.run_cached_attention) and
by the XLA training/prefill fallback in ops/flash_attention.py.  The
Pallas flash kernels get the same property via BlockSpec index maps
(group members read the same kv block) rather than these einsums.
"""
from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

_NEG_INF = -1e30
# Symmetric int8/int16 ranges for the KV-cache / activation quant.
_INT8_MAX = 127.0
_INT16_MAX = 32767.0
# Absmax floor: an all-zero row (cache padding, masked slots) must
# quantize to zeros with a finite scale, not divide by zero.
_SCALE_FLOOR = 1e-8


def grouped_attention(q: jax.Array, keys: jax.Array, values: jax.Array,
                      mask: Optional[jax.Array], *, scale: float,
                      probs_dtype: Any) -> jax.Array:
    """Masked softmax attention with unbroadcast grouped K/V.

    q:      [B, H, Sq, dk]   (any dtype; scores accumulate in f32)
    keys:   [B, kvh, Sk, dk] with H % kvh == 0 — NOT repeated to H
    values: [B, kvh, Sk, dv]
    mask:   bool, broadcastable to [B, 1, Sq, Sk] (or None = no mask)
    scale:  score multiplier (callers pass dk**-0.5 or a custom scale)
    probs_dtype: dtype the probabilities are cast to before the PV
        matmul (the cache/compute dtype) — matches the old epilogue.

    Returns [B, Sq, H, dv].
    """
    b, h, sq, _ = q.shape
    kvh = keys.shape[1]
    if h % kvh:
        raise ValueError(
            f'query heads ({h}) not divisible by kv heads ({kvh})')
    qf = q.astype(jnp.float32)
    kf = keys.astype(jnp.float32)
    if kvh == h:
        # MHA (GPT-2 and kvh==H configs): plain per-head contraction.
        scores = jnp.einsum('bhqd,bhkd->bhqk', qf, kf) * scale
        if mask is not None:
            scores = jnp.where(mask, scores, _NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum('bhqk,bhkd->bhqd', probs.astype(probs_dtype),
                         values)
    elif kvh == 1:
        # Latent/MQA fast branch: ONE shared kv head (DeepSeek's
        # absorbed decode scores all H query heads directly against the
        # single [B, 1, S, rkv+dr] latent) — drop the unit head axis
        # instead of carrying a size-1 group dim through the einsum.
        scores = jnp.einsum('bhqd,bkd->bhqk', qf, kf[:, 0]) * scale
        if mask is not None:
            scores = jnp.where(mask, scores, _NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum('bhqk,bkd->bhqd', probs.astype(probs_dtype),
                         values[:, 0])
    else:
        # Grouped: [B, kvh, G, Sq, d] x [B, kvh, Sk, d] — the G query
        # heads sharing a kv head ride the same contraction, so the kv
        # operand is read once per group instead of once per head.
        g = h // kvh
        qg = qf.reshape(b, kvh, g, sq, qf.shape[-1])
        scores = jnp.einsum('bngqd,bnkd->bngqk', qg, kf) * scale
        if mask is not None:
            # [B|1, 1, Sq, Sk] -> [B|1, 1, 1, Sq, Sk]: broadcast over
            # both the kv-head and group axes.
            scores = jnp.where(mask[:, :, None], scores, _NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum('bngqk,bnkd->bngqd', probs.astype(probs_dtype),
                         values)
        out = out.reshape(b, h, sq, values.shape[-1])
    return jnp.transpose(out, (0, 2, 1, 3))


def gather_pages(pool: jax.Array, table: jax.Array) -> jax.Array:
    """Assemble per-row contiguous K/V views from a paged cache pool.

    pool:  [n_pages, kvh, page_size, d] — the physical page pool (K, V,
           or an int8 sibling scale pool with d == 1).
    table: [B, n_read] int32 — each row's block table, truncated to the
           n_read logical pages the decode step actually reads (the
           bucketed high-water mark divided by page_size).  Entries for
           pages a row never allocated point at the reserved null page
           0; their content is garbage that kv_mask hides.

    Returns [B, kvh, n_read * page_size, d]: position j of the result
    is the row's absolute cache slot j, so kv_mask / sliding-window
    semantics carry over from the contiguous layout unchanged.  One
    gather per pool per step — HBM reads scale with n_read (allocated,
    live pages), not max_seq_len.
    """
    b, n_read = table.shape
    _, kvh, ps, d = pool.shape
    g = jnp.take(pool, table.reshape(-1), axis=0)
    g = g.reshape(b, n_read, kvh, ps, d)
    return jnp.transpose(g, (0, 2, 1, 3, 4)).reshape(
        b, kvh, n_read * ps, d)


def quantize_int8_rows(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-row symmetric int8 absmax quantization over the LAST axis.

    For a cache write x [..., d] returns (q int8 [..., d],
    scale f32 [..., 1]) with x ~= q * scale.  One scale per
    (kv-head, position) row — the granularity the quantized epilogue
    can fold into the score/PV contractions without ever materializing
    a dequantized copy of the cache.
    """
    xf = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1, keepdims=True),
                        _SCALE_FLOOR) / _INT8_MAX
    q = jnp.clip(jnp.round(xf / scale), -_INT8_MAX,
                 _INT8_MAX).astype(jnp.int8)
    return q, scale


def _quantize_int16_rows(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-row int16 absmax quant for the ACTIVATION side of the
    integer dots (queries, value-scaled probs).  int16 keeps the
    activation quant error ~256x below the int8 cache's own error
    floor, so the quantized path's accuracy is set by the cache quant
    alone — while the dot still runs integer x integer and never
    widens the cache to float in HBM."""
    xf = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1, keepdims=True),
                        _SCALE_FLOOR) / _INT16_MAX
    q = jnp.clip(jnp.round(xf / scale), -_INT16_MAX,
                 _INT16_MAX).astype(jnp.int16)
    return q, scale


def _int_dot(a16: jax.Array, b8: jax.Array, *, contract_a: int,
             contract_b: int, batch_dims: int) -> jax.Array:
    """lax.dot_general int16 x int8 -> int32 with leading batch dims."""
    batch = tuple(range(batch_dims))
    return jax.lax.dot_general(
        a16, b8, (((contract_a,), (contract_b,)), (batch, batch)),
        preferred_element_type=jnp.int32)


def quantized_grouped_attention(q: jax.Array, keys_q: jax.Array,
                                key_scale: jax.Array,
                                values_q: jax.Array,
                                value_scale: jax.Array,
                                mask: Optional[jax.Array], *,
                                scale: float,
                                probs_dtype: Any) -> jax.Array:
    """grouped_attention against an int8 cache, dequant fused.

    q:           [B, H, Sq, dk]  float (quantized to int16 per row here)
    keys_q:      [B, kvh, Sk, dk]  int8
    key_scale:   [B, kvh, Sk, 1]   f32 per-(kv-head, position) absmax
    values_q:    [B, kvh, Sk, dv]  int8
    value_scale: [B, kvh, Sk, 1]   f32
    mask/scale/probs_dtype: as grouped_attention.

    The score dot contracts int16 queries against the int8 keys
    (int32 accumulate — exact); k_scale sits outside the contracted
    head_dim axis, so it multiplies the int32 scores afterwards.
    v_scale sits ON the contracted position axis of the PV dot, so it
    is folded into the probabilities BEFORE they are requantized to
    int16 for the second integer dot.  No f32/bf16 tensor of the full
    cache shape ever materializes — the bandwidth property the
    compiled-HLO tests pin down.

    Returns [B, Sq, H, dv].
    """
    b, h, sq, _ = q.shape
    kvh = keys_q.shape[1]
    if h % kvh:
        raise ValueError(
            f'query heads ({h}) not divisible by kv heads ({kvh})')
    dv = values_q.shape[-1]
    if kvh == h:
        # MHA: per-head integer contraction.
        qq, qs = _quantize_int16_rows(q)
        scores = _int_dot(qq, keys_q, contract_a=3, contract_b=3,
                          batch_dims=2).astype(jnp.float32)
        # [B, kvh, Sk, 1] -> [B, kvh, 1, Sk] (broadcast over Sq).
        scores = scores * qs * key_scale[:, :, None, :, 0] * scale
        if mask is not None:
            scores = jnp.where(mask, scores, _NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        pscaled = probs * value_scale[:, :, None, :, 0]
        pq, ps = _quantize_int16_rows(pscaled)
        out = _int_dot(pq, values_q, contract_a=3, contract_b=2,
                       batch_dims=2).astype(jnp.float32) * ps
    elif kvh == 1:
        # Latent/MQA branch: drop the unit kv-head axis (DeepSeek's
        # absorbed decode scores all H heads against one latent row).
        qq, qs = _quantize_int16_rows(q)
        scores = jax.lax.dot_general(
            qq, keys_q[:, 0], (((3,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.int32).astype(jnp.float32)
        # [B, 1, Sk, 1] -> [B, 1, 1, Sk] (broadcast over H and Sq).
        ks = key_scale[:, 0, :, 0][:, None, None, :]
        scores = scores * qs * ks * scale
        if mask is not None:
            scores = jnp.where(mask, scores, _NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        pscaled = probs * value_scale[:, 0, :, 0][:, None, None, :]
        pq, ps = _quantize_int16_rows(pscaled)
        out = jax.lax.dot_general(
            pq, values_q[:, 0], (((3,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.int32).astype(jnp.float32) * ps
    else:
        # Grouped: [B, kvh, G, Sq, d] x [B, kvh, Sk, d] int dot.
        g = h // kvh
        qg = q.reshape(b, kvh, g, sq, q.shape[-1])
        qq, qs = _quantize_int16_rows(qg)
        scores = _int_dot(qq, keys_q, contract_a=4, contract_b=3,
                          batch_dims=2).astype(jnp.float32)
        # key_scale [B, kvh, Sk, 1] -> [B, kvh, 1, 1, Sk].
        scores = scores * qs * key_scale[:, :, None, None, :, 0] * scale
        if mask is not None:
            scores = jnp.where(mask[:, :, None], scores, _NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        pscaled = probs * value_scale[:, :, None, None, :, 0]
        pq, ps = _quantize_int16_rows(pscaled)
        out = _int_dot(pq, values_q, contract_a=4, contract_b=2,
                       batch_dims=2).astype(jnp.float32) * ps
        out = out.reshape(b, h, sq, dv)
    return jnp.transpose(out.astype(probs_dtype), (0, 2, 1, 3))
