"""Grouped-query attention einsums that never broadcast K/V to H heads.

Decode is bandwidth-bound: with GQA (kvh < H) the old cached-attention
epilogue `jnp.repeat`-ed keys/values up to H query heads before the
score matmul, materializing a [B, H, S, d] operand in HBM every layer
every step — an h/kvh-fold inflation of the per-step cache working set.
For DeepSeek's absorbed MLA decode (ONE latent head, H up to 128) that
silently undid the latent-cache bandwidth win.

The fix is free: reshape queries to [B, kvh, H/kvh, Sq, d] and contract
against the *unbroadcast* [B, kvh, Sk, d] cache, so the head-group
broadcast happens inside the einsum (a batched matmul with the group
folded into the row dim — XLA never materializes the repeated operand).
Numerics are bit-identical to repeat-then-matmul: each (query head,
position) dot product sums the same values in the same order.

Shared by every family's decode path (llama.run_cached_attention) and
by the XLA training/prefill fallback in ops/flash_attention.py.  The
Pallas flash kernels get the same property via BlockSpec index maps
(group members read the same kv block) rather than these einsums.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def grouped_attention(q: jax.Array, keys: jax.Array, values: jax.Array,
                      mask: Optional[jax.Array], *, scale: float,
                      probs_dtype: Any) -> jax.Array:
    """Masked softmax attention with unbroadcast grouped K/V.

    q:      [B, H, Sq, dk]   (any dtype; scores accumulate in f32)
    keys:   [B, kvh, Sk, dk] with H % kvh == 0 — NOT repeated to H
    values: [B, kvh, Sk, dv]
    mask:   bool, broadcastable to [B, 1, Sq, Sk] (or None = no mask)
    scale:  score multiplier (callers pass dk**-0.5 or a custom scale)
    probs_dtype: dtype the probabilities are cast to before the PV
        matmul (the cache/compute dtype) — matches the old epilogue.

    Returns [B, Sq, H, dv].
    """
    b, h, sq, _ = q.shape
    kvh = keys.shape[1]
    if h % kvh:
        raise ValueError(
            f'query heads ({h}) not divisible by kv heads ({kvh})')
    qf = q.astype(jnp.float32)
    kf = keys.astype(jnp.float32)
    if kvh == h:
        # MHA (GPT-2 and kvh==H configs): plain per-head contraction.
        scores = jnp.einsum('bhqd,bhkd->bhqk', qf, kf) * scale
        if mask is not None:
            scores = jnp.where(mask, scores, _NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum('bhqk,bhkd->bhqd', probs.astype(probs_dtype),
                         values)
    elif kvh == 1:
        # Latent/MQA fast branch: ONE shared kv head (DeepSeek's
        # absorbed decode scores all H query heads directly against the
        # single [B, 1, S, rkv+dr] latent) — drop the unit head axis
        # instead of carrying a size-1 group dim through the einsum.
        scores = jnp.einsum('bhqd,bkd->bhqk', qf, kf[:, 0]) * scale
        if mask is not None:
            scores = jnp.where(mask, scores, _NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum('bhqk,bkd->bhqd', probs.astype(probs_dtype),
                         values[:, 0])
    else:
        # Grouped: [B, kvh, G, Sq, d] x [B, kvh, Sk, d] — the G query
        # heads sharing a kv head ride the same contraction, so the kv
        # operand is read once per group instead of once per head.
        g = h // kvh
        qg = qf.reshape(b, kvh, g, sq, qf.shape[-1])
        scores = jnp.einsum('bngqd,bnkd->bngqk', qg, kf) * scale
        if mask is not None:
            # [B|1, 1, Sq, Sk] -> [B|1, 1, 1, Sq, Sk]: broadcast over
            # both the kv-head and group axes.
            scores = jnp.where(mask[:, :, None], scores, _NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum('bngqk,bnkd->bngqd', probs.astype(probs_dtype),
                         values)
        out = out.reshape(b, h, sq, values.shape[-1])
    return jnp.transpose(out, (0, 2, 1, 3))
