"""Ring attention: exact attention over sequence shards on an ICI ring.

The reference has NO sequence/context parallelism (SURVEY.md §2.11 — "no
hits for ring-attention/Ulysses"); this is green-field TPU design:

  - the sequence is sharded over the mesh's `context` axis; each device
    holds q/k/v chunks [B, H, S/c, D] (k/v may carry kvh < H heads —
    GQA chunks rotate unbroadcast, an h/kvh-fold ICI traffic saving);
  - c ring steps: compute blockwise attention of the local q chunk
    against the currently-held kv chunk (Pallas flash kernel), merge with
    the running (out, lse) online-softmax state, then rotate kv to the
    ICI neighbor with `jax.lax.ppermute` — communication overlaps compute
    and total memory stays O(S/c) per device (Liu et al., Ring Attention
    with Blockwise Transformers);
  - backward is a second ring pass (FlashAttention-2 block math) where
    (k, v, dk, dv) travel the ring together and return to their owners —
    the whole op is a custom_vjp so autodiff never sees the loop;
  - causal masking is applied per (q_chunk, kv_chunk) pair from the ring
    offsets; fully-masked pairs skip the kernel via lax.cond.

Must be called under shard_map (or an equivalent axis context) with the
sequence dimension sharded over `axis_name`.  `ulysses_attention` is the
all-to-all head-scatter alternative for meshes where a ring is a poor
fit.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from skypilot_tpu.ops import flash_attention as fa

_NEG_INF = -1e30


def _axis_size(axis_name: str) -> int:
    """Static size of a bound mesh axis.  `jax.lax.axis_size` where it
    exists; older jax constant-folds `psum(1, axis)` to the same int."""
    size_fn = getattr(jax.lax, 'axis_size', None)
    if size_fn is not None:
        return size_fn(axis_name)
    return jax.lax.psum(1, axis_name)


def _merge(out1, lse1, out2, lse2):
    """Online-softmax merge of two partial attention results."""
    lse_new = jnp.logaddexp(lse1, lse2)
    w1 = jnp.exp(lse1 - lse_new)[..., None]
    w2 = jnp.exp(lse2 - lse_new)[..., None]
    return out1 * w1 + out2 * w2, lse_new


def _block_fwd(q, k, v, scale, q_off, k_off, chunk):
    """(out, lse) of one q-chunk vs one kv-chunk with global causal mask.

    Three cases by ring offset: kv strictly ahead of q → fully masked;
    same chunk → causal within; kv behind → full attention.
    """
    vma = fa._out_vma(q, k, v)  # pylint: disable=protected-access

    def full(_):
        return fa._fwd_impl(q, k, v, scale, False,  # pylint: disable=protected-access
                            fa.DEFAULT_BLOCK_Q, fa.DEFAULT_BLOCK_KV)

    def diag(_):
        return fa._fwd_impl(q, k, v, scale, True,  # pylint: disable=protected-access
                            fa.DEFAULT_BLOCK_Q, fa.DEFAULT_BLOCK_KV)

    def masked(_):
        # Fresh arrays must carry the manual-axes type of the real
        # branches (varying over the context axis).
        return (fa._cast_vma(jnp.zeros_like(q), vma),  # pylint: disable=protected-access
                fa._cast_vma(jnp.full(q.shape[:-1], _NEG_INF,  # pylint: disable=protected-access
                                      jnp.float32), vma))

    return jax.lax.cond(
        k_off > q_off, masked,
        lambda _: jax.lax.cond(k_off == q_off, diag, full, None), None)


def _use_windowed_ring(window, causal: bool, s_local: int,
                       axis_size: int) -> bool:
    """ONE predicate for both the forward and backward dispatch —
    if they disagreed, custom_vjp would silently pair a full-ring
    forward with a windowed backward (or vice versa)."""
    return (window is not None and causal
            and window < s_local * axis_size)


def _window_max_distance(window: int, s_local: int,
                         axis_size: int) -> int:
    """Largest chunk distance d such that a q chunk still attends
    into the kv chunk d hops behind it: the kv chunk's last position
    (d*s_local closer) must be >= the q chunk's first position minus
    (window-1)."""
    return min(axis_size - 1, (window + s_local - 2) // s_local)


def _ring_fwd_loop_windowed(q, k, v, scale, axis_name, axis_size,
                            window):
    """Sliding-window ring forward: a STATIC Python loop over chunk
    distances 0..max_d instead of the full fori over axis_size —
    chunks beyond the window are never computed NOR rotated.  For
    Mistral-like shapes (window == s_local) that is 2 ring steps
    instead of axis_size: ~axis_size/2 x less ICI traffic.

    Static unroll is the point: the per-distance band offset
    (d * s_local) must be a compile-time constant for the flash
    kernel's block-skip logic.
    """
    my = jax.lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    max_d = _window_max_distance(window, s_local, axis_size)
    vma = fa._out_vma(q, k, v)  # pylint: disable=protected-access
    out = fa._cast_vma(jnp.zeros((b, h, s_local, d), jnp.float32), vma)  # pylint: disable=protected-access
    lse = fa._cast_vma(jnp.full((b, h, s_local), _NEG_INF, jnp.float32),  # pylint: disable=protected-access
                       vma)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    k_cur, v_cur = k, v
    for t in range(max_d + 1):
        if t == 0:
            part_out, part_lse = fa._fwd_impl(  # pylint: disable=protected-access
                q, k_cur, v_cur, scale, True, fa.DEFAULT_BLOCK_Q,
                fa.DEFAULT_BLOCK_KV, window=window)
        else:
            offset = t * s_local

            def banded(_, k_c=k_cur, v_c=v_cur, off=offset):
                return fa._fwd_impl(  # pylint: disable=protected-access
                    q, k_c, v_c, scale, True, fa.DEFAULT_BLOCK_Q,
                    fa.DEFAULT_BLOCK_KV, window=window, offset=off)

            def masked(_):
                # Output dtypes must match banded's (q dtype out,
                # f32 lse) for the cond.
                return (fa._cast_vma(jnp.zeros_like(q), vma),  # pylint: disable=protected-access
                        fa._cast_vma(jnp.full(q.shape[:-1], _NEG_INF,  # pylint: disable=protected-access
                                              jnp.float32), vma))

            # Ranks whose t-behind neighbor wraps around (my < t)
            # would be attending the sequence END — future tokens.
            part_out, part_lse = jax.lax.cond(my >= t, banded, masked,
                                              None)
        out, lse = _merge(out, lse, part_out.astype(jnp.float32),
                          part_lse)
        if t < max_d:
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
    return out.astype(q.dtype), lse


def _ring_fwd_loop(q, k, v, scale, axis_name, axis_size, causal):
    my = jax.lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    vma = fa._out_vma(q, k, v)  # pylint: disable=protected-access
    out = fa._cast_vma(jnp.zeros((b, h, s_local, d), jnp.float32), vma)  # pylint: disable=protected-access
    lse = fa._cast_vma(jnp.full((b, h, s_local), _NEG_INF, jnp.float32),  # pylint: disable=protected-access
                       vma)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(t, carry):
        out, lse, k_cur, v_cur = carry
        src = (my - t) % axis_size
        if causal:
            part_out, part_lse = _block_fwd(q, k_cur, v_cur, scale, my,
                                            src, s_local)
        else:
            part_out, part_lse = fa._fwd_impl(  # pylint: disable=protected-access
                q, k_cur, v_cur, scale, False, fa.DEFAULT_BLOCK_Q,
                fa.DEFAULT_BLOCK_KV)
        out, lse = _merge(out, lse, part_out.astype(jnp.float32),
                          part_lse)
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return out, lse, k_next, v_next

    out, lse, _, _ = jax.lax.fori_loop(0, axis_size, step,
                                       (out, lse, k, v))
    return out.astype(q.dtype), lse


def _block_bwd(q, k, v, do, lse, delta, scale, q_off, k_off):
    """FA2 block backward for one (q_chunk, kv_chunk) pair.

    Reuses the flash backward (Pallas on TPU) with the global lse/delta
    — O(block) attention materialization instead of the full
    [chunk x chunk] probability matrix.  Three cases by ring offset,
    like the forward: kv strictly ahead → zero grads; same chunk →
    causal; kv behind → full attention.
    """
    vma = fa._out_vma(q, k, v, do)  # pylint: disable=protected-access

    def masked(_):
        z = lambda x: fa._cast_vma(  # pylint: disable=protected-access
            jnp.zeros(x.shape, jnp.float32), vma)
        return z(q), z(k), z(v)

    def diag(_):
        return fa._pair_bwd(q, k, v, do, lse, delta,  # pylint: disable=protected-access
                            scale=scale, causal=True)

    def full(_):
        return fa._pair_bwd(q, k, v, do, lse, delta,  # pylint: disable=protected-access
                            scale=scale, causal=False)

    return jax.lax.cond(
        k_off > q_off, masked,
        lambda _: jax.lax.cond(k_off == q_off, diag, full, None), None)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str = 'context',
                   causal: bool = True,
                   scale: Optional[float] = None,
                   window: Optional[int] = None) -> jax.Array:
    out, _ = _ring_fwd(q, k, v, axis_name, causal, scale, window)
    return out


def _ring_fwd(q, k, v, axis_name, causal, scale, window=None):
    actual_scale = scale if scale is not None else q.shape[-1] ** -0.5
    axis_size = _axis_size(axis_name)
    if window is not None and not causal:
        raise ValueError('window requires causal=True')
    if _use_windowed_ring(window, causal, q.shape[2], axis_size):
        return _ring_fwd_loop_windowed(q, k, v, actual_scale,
                                       axis_name, axis_size, window)
    # window >= full sequence: plain full ring is identical.
    return _ring_fwd_loop(q, k, v, actual_scale, axis_name, axis_size,
                          causal)


def _ring_vjp_fwd(q, k, v, axis_name, causal, scale, window=None):
    out, lse = _ring_fwd(q, k, v, axis_name, causal, scale, window)
    return out, (q, k, v, out, lse)


def _ring_bwd_windowed(q, k, v, g, lse, delta, scale, axis_name,
                       axis_size, window):
    """Backward mirror of the windowed forward: distances 0..max_d
    only, accumulators riding the rotating kv, then ONE collective
    permute delivering each chunk's grads home (the full ring does
    axis_size rotations; early exit leaves them (max_d) hops away)."""
    my = jax.lax.axis_index(axis_name)
    s_local = q.shape[2]
    max_d = _window_max_distance(window, s_local, axis_size)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    vma = fa._out_vma(q, k, v, g)  # pylint: disable=protected-access
    dq = fa._cast_vma(jnp.zeros(q.shape, jnp.float32), vma)  # pylint: disable=protected-access
    dk_cur = fa._cast_vma(jnp.zeros(k.shape, jnp.float32), vma)  # pylint: disable=protected-access
    dv_cur = fa._cast_vma(jnp.zeros(v.shape, jnp.float32), vma)  # pylint: disable=protected-access
    k_cur, v_cur = k, v
    for t in range(max_d + 1):
        if t == 0:
            dq_t, dk_t, dv_t = fa._pair_bwd(  # pylint: disable=protected-access
                q, k_cur, v_cur, g, lse, delta, scale=scale,
                causal=True, window=window)
        else:
            offset = t * s_local

            def banded(_, k_c=k_cur, v_c=v_cur, off=offset):
                return fa._pair_bwd(  # pylint: disable=protected-access
                    q, k_c, v_c, g, lse, delta, scale=scale,
                    causal=True, window=window, offset=off)

            def masked(_):
                z = lambda x: fa._cast_vma(  # pylint: disable=protected-access
                    jnp.zeros(x.shape, jnp.float32), vma)
                return z(q), z(k), z(v)

            dq_t, dk_t, dv_t = jax.lax.cond(my >= t, banded, masked,
                                            None)
        dq = dq + dq_t
        dk_cur = dk_cur + dk_t
        dv_cur = dv_cur + dv_t
        if t < max_d:
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
            dk_cur = jax.lax.ppermute(dk_cur, axis_name, perm)
            dv_cur = jax.lax.ppermute(dv_cur, axis_name, perm)
    # dk_cur now holds chunk (my - max_d)'s grads: max_d rotations
    # happened, so deliver home with one permute of the remaining
    # (axis_size - max_d) hops.
    if max_d:
        home = [(i, (i + axis_size - max_d) % axis_size)
                for i in range(axis_size)]
        dk_cur = jax.lax.ppermute(dk_cur, axis_name, home)
        dv_cur = jax.lax.ppermute(dv_cur, axis_name, home)
    return (dq.astype(q.dtype), dk_cur.astype(k.dtype),
            dv_cur.astype(v.dtype))


def _ring_vjp_bwd(axis_name, causal, scale, window, residuals, g):
    q, k, v, out, lse = residuals
    actual_scale = scale if scale is not None else q.shape[-1] ** -0.5
    axis_size = _axis_size(axis_name)
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)
    if _use_windowed_ring(window, causal, q.shape[2], axis_size):
        return _ring_bwd_windowed(q, k, v, g, lse, delta,
                                  actual_scale, axis_name, axis_size,
                                  window)
    my = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    vma = fa._out_vma(q, k, v, g)  # pylint: disable=protected-access
    dq = fa._cast_vma(jnp.zeros(q.shape, jnp.float32), vma)  # pylint: disable=protected-access
    dk0 = fa._cast_vma(jnp.zeros(k.shape, jnp.float32), vma)  # pylint: disable=protected-access
    dv0 = fa._cast_vma(jnp.zeros(v.shape, jnp.float32), vma)  # pylint: disable=protected-access

    def step(t, carry):
        dq, k_cur, v_cur, dk_cur, dv_cur = carry
        src = (my - t) % axis_size
        q_off = my if causal else jnp.int32(1)
        k_off = src if causal else jnp.int32(0)
        dq_t, dk_t, dv_t = _block_bwd(q, k_cur, v_cur, g, lse, delta,
                                      actual_scale, q_off, k_off)
        dq = dq + dq_t
        dk_cur = dk_cur + dk_t
        dv_cur = dv_cur + dv_t
        # Rotate kv and its accumulating grads together: after axis_size
        # steps they are back at the owner.
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        dk_cur = jax.lax.ppermute(dk_cur, axis_name, perm)
        dv_cur = jax.lax.ppermute(dv_cur, axis_name, perm)
        return dq, k_cur, v_cur, dk_cur, dv_cur

    dq, _, _, dk, dv = jax.lax.fori_loop(
        0, axis_size, step, (dq, k, v, dk0, dv0))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


ring_attention.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)


def _in_manual_region(axis_name: str) -> bool:
    """True when already inside a shard_map manual over `axis_name`."""
    try:
        _axis_size(axis_name)
        return True
    except (NameError, KeyError, ValueError):
        return False


def context_parallel_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                               *, causal: bool = True,
                               impl: str = 'ring',
                               axis_name: str = 'context',
                               window: Optional[int] = None
                               ) -> jax.Array:
    """Context-parallel attention inside an auto-sharded (pjit) graph.

    Wraps ring/ulysses attention in a shard_map that is manual ONLY
    over the context axis of the ambient mesh (other axes — data/fsdp/
    tensor — stay compiler-partitioned), sharding the sequence dim.
    Falls back to plain flash attention when no mesh with a context
    axis > 1 is active, so models can call this unconditionally.
    """
    from jax.sharding import PartitionSpec as P

    from skypilot_tpu.parallel import sharding as sharding_lib
    fn = ring_attention if impl == 'ring' else ulysses_attention
    if _in_manual_region(axis_name):
        # Already inside a shard_map manual over the context axis (e.g.
        # a pipeline stage manual over {'pipe','context'}): q/k/v are
        # the local sequence shards — no nested shard_map.  Off-TPU the
        # XLA CPU backend crashes on low-precision collectives nested
        # in partial-manual scans ("Invalid binary instruction opcode
        # copy" — same bug parallel/pipeline.py works around), so the
        # ring runs in f32 there; on TPU it stays in the model dtype.
        if (jax.default_backend() != 'tpu'
                and q.dtype in (jnp.bfloat16, jnp.float16)):
            out = fn(q.astype(jnp.float32), k.astype(jnp.float32),
                     v.astype(jnp.float32), axis_name=axis_name,
                     causal=causal, window=window)
            return out.astype(q.dtype)
        return fn(q, k, v, axis_name=axis_name, causal=causal,
                  window=window)
    mesh = sharding_lib.ambient_physical_mesh()
    if mesh is None or mesh.shape.get(axis_name, 1) == 1:
        return fa.flash_attention(q, k, v, None, causal,
                                  fa.DEFAULT_BLOCK_Q,
                                  fa.DEFAULT_BLOCK_KV, window)
    spec = P(None, None, axis_name, None)
    # Deliberately jax.shard_map (not the compat shim): on older jax
    # the experimental partial-manual fallback compiles here but then
    # dies inside GSPMD ("PartitionId ... UNIMPLEMENTED", or a hard
    # XLA abort for ulysses) whenever the auto complement has
    # nontrivial axes (data/tensor > 1), which this training path
    # always has.  An AttributeError at trace time is diagnosable; a
    # backend abort kills the process.
    wrapped = jax.shard_map(
        functools.partial(fn, axis_name=axis_name, causal=causal,
                          window=window),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        axis_names=frozenset({axis_name}))
    return wrapped(q, k, v)


# ---------------------------------------------------------------------------
# Ulysses (all-to-all head scatter) alternative
# ---------------------------------------------------------------------------
def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str = 'context',
                      causal: bool = True,
                      window: Optional[int] = None) -> jax.Array:
    """DeepSpeed-Ulysses-style context parallelism: all-to-all converts
    sequence sharding into head sharding, attention runs unsharded per
    head group, and a second all-to-all restores sequence sharding.
    Cheaper than a ring when heads >= axis_size and sequence is moderate;
    the ring wins at very long context (SURVEY.md §5).
    Inputs per shard: [B, H, S/c, D]; requires H % c == 0.  K/V may
    carry kvh < H heads (GQA): when kvh divides c they are scattered
    unbroadcast (the flash kernel keeps the group contraction); when it
    doesn't (e.g. MQA kvh=1 on a 2-wide axis) K/V are head-broadcast
    first — ulysses fundamentally shards heads, so there is no
    unbroadcast layout to scatter.  Prefer the ring for those shapes.
    """
    c = _axis_size(axis_name)
    heads, kvh = q.shape[1], k.shape[1]
    if kvh != heads and kvh % c != 0:
        k = jnp.repeat(k, heads // kvh, axis=1)
        v = jnp.repeat(v, heads // kvh, axis=1)

    # tiled all_to_all: split_axis is divided into c chunks that land
    # concatenated along concat_axis — [B, H, S/c, D] <-> [B, H/c, S, D]
    # in one collective each way, no reshape bookkeeping.
    def scatter_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    def gather_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    q_h = scatter_heads(q)
    k_h = scatter_heads(k)
    v_h = scatter_heads(v)
    out = fa.flash_attention(q_h, k_h, v_h, None, causal,
                             fa.DEFAULT_BLOCK_Q, fa.DEFAULT_BLOCK_KV,
                             window)
    return gather_heads(out)
