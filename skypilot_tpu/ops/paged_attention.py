"""Fused paged-attention decode kernel for TPU (Pallas).

The XLA paged decode path (models/llama.py `_paged_slot_attention`)
assembles each row's pages into a contiguous [B, kvh, n_read*ps, d]
view (`ops/grouped_attention.gather_pages`) before the grouped einsum
runs — an extra HBM round-trip (write + re-read of the gathered copy,
plus the int8 scale siblings) that grows with live context, exactly
the bytes the paging + int8 PRs fought to save.

This kernel walks the block table *inside* the kernel instead: the
table rides in as a scalar-prefetch operand, and each (row, kv-head,
logical-page) program's K/V BlockSpec index map dereferences it —
`(table[b, j], h, 0, 0)` — so one [page_size, d] tile streams from the
physical pool straight into VMEM per grid step.  Fused in the same
program, with zero intermediate HBM tensors:

  - page gather (the BlockSpec indirection above);
  - int8 dequant: the sibling per-(kv-head, position) f32 scale pages
    are folded into the dots — key scales multiply the score columns
    after the q.k contraction, value scales fold into the
    probabilities before the PV contraction — so no float copy of the
    cache ever exists, mirroring `quantized_grouped_attention`;
  - grouped attention: the G = H/kvh query heads sharing a kv head ride
    one program as a [G*S, d] q block (same unbroadcast-K/V property as
    the grouped einsums and the flash kernels);
  - online-softmax accumulation across the row's pages (f32 m/l/acc in
    VMEM scratch, init at page 0, finalize at the last page);
  - the s>1 speculative-verify window semantics: visibility arrives as
    the SAME [B, 1, S, read_len] mask the XLA path computes (revealed
    slots, per-query verify windows, sliding window, null-page-0
    entries all pre-encoded), sliced per page by the BlockSpec.

Off-TPU the kernel runs in interpreter mode (tests); serving defaults
never select it off-TPU — the XLA gather path stays the production
fallback and parity oracle (see `--decode-kernel` on the engine).
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from skypilot_tpu.parallel import mesh as mesh_lib

_NEG_INF = -1e30
_TENSOR_AXIS = mesh_lib.AXIS_TENSOR


def _on_tpu() -> bool:
    return jax.default_backend() == 'tpu'


def _decode_kernel_body(refs, *, scale: float, group: int, s: int,
                        quant: bool) -> None:
    """One grid step: fold page j of row b / kv-head h into the
    running online-softmax state.  Grid is (B, kvh, n_read) with the
    page axis innermost, so the o/scratch blocks stay VMEM-resident
    across a row's whole page sweep (the Pallas revisiting rule)."""
    if quant:
        (_, q_ref, k_ref, v_ref, ks_ref, vs_ref, mask_ref,
         o_ref, m_ref, l_ref, acc_ref) = refs
    else:
        (_, q_ref, k_ref, v_ref, mask_ref,
         o_ref, m_ref, l_ref, acc_ref) = refs
    j = pl.program_id(2)
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q = q_ref[0, 0].astype(jnp.float32)            # [G*S, d]
    k = k_ref[0, 0].astype(jnp.float32)            # [ps, d]
    v = v_ref[0, 0].astype(jnp.float32)            # [ps, d]
    ps = k.shape[0]
    sc = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale   # [G*S, ps]
    if quant:
        # Key scales sit outside the contracted head_dim axis: they
        # multiply the int-valued score columns, never a K tile copy.
        sc = sc * ks_ref[0, 0][:, 0][None, :]
    keep = mask_ref[0]                             # [S, ps]
    if group > 1:
        keep = jnp.broadcast_to(
            keep[None], (group, s, ps)).reshape(group * s, ps)
    sc = jnp.where(keep, sc, _NEG_INF)
    m_prev = m_ref[:, :1]                          # [G*S, 1]
    m_cur = jnp.max(sc, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(sc - m_new)                        # [G*S, ps]
    correction = jnp.exp(m_prev - m_new)
    l_new = correction * l_ref[:, :1] + jnp.sum(p, axis=1,
                                                keepdims=True)
    if quant:
        # Value scales sit ON the contracted position axis of the PV
        # dot: fold them into the probabilities, keep V int-valued.
        p = p * vs_ref[0, 0][:, 0][None, :]
    acc_ref[:] = acc_ref[:] * correction + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nj - 1)
    def _finalize():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


def _in_manual_region(axis_name: str) -> bool:
    """True when already inside a shard_map manual over `axis_name`
    (e.g. a re-entrant trace) — the inputs are then local shards and
    wrapping again would double-shard."""
    try:
        size_fn = getattr(jax.lax, 'axis_size', None)
        if size_fn is not None:
            size_fn(axis_name)
        else:
            jax.lax.psum(1, axis_name)
        return True
    except (NameError, KeyError, ValueError):
        return False


def paged_decode_attention(q: jax.Array, page_key: jax.Array,
                           page_value: jax.Array, table: jax.Array,
                           mask: jax.Array, *, scale: float,
                           probs_dtype: Any,
                           key_scale: Optional[jax.Array] = None,
                           value_scale: Optional[jax.Array] = None,
                           interpret: Optional[bool] = None
                           ) -> jax.Array:
    """Decode attention straight from the paged KV pools.

    Under an ambient mesh with `tensor > 1` (the engine's decode step
    traces inside `with mesh:`), the kernel self-lowers through
    shard_map manual over the tensor axis: each chip walks the block
    table over its LOCAL kv-head shard of the pools — q's head axis
    splits into the same contiguous kv-head-major chunks (head index =
    kv_head * G + member, so H-shards and kvh-shards align exactly),
    the replicated table/mask ride in whole, and the [B, S, H, d]
    output stays head-sharded for the downstream o_proj row-parallel
    psum (the same collective the MLP already pays).  No collective
    runs inside the kernel: softmax is per-head.  See
    `_paged_decode_attention_impl` for the single-shard contract.
    """
    mesh = None
    if not _in_manual_region(_TENSOR_AXIS):
        from skypilot_tpu.parallel import sharding as sharding_lib
        mesh = sharding_lib.ambient_physical_mesh()
    tensor = mesh.shape.get(_TENSOR_AXIS, 1) if mesh is not None else 1
    if tensor <= 1:
        return _paged_decode_attention_impl(
            q, page_key, page_value, table, mask, scale=scale,
            probs_dtype=probs_dtype, key_scale=key_scale,
            value_scale=value_scale, interpret=interpret)
    kvh = page_key.shape[1]
    if kvh % tensor:
        # Startup validation (engine.resolve_decode_kernel) refuses
        # this combination; raising here too turns any path that slips
        # through into a diagnosable error instead of a Pallas
        # partitioning crash.
        raise ValueError(
            f'fused paged decode under tensor={tensor} needs the pool '
            f'kv-head axis ({kvh}) divisible by it; this geometry '
            "(DeepSeek latent kvh==1) must use decode_kernel='xla' "
            'over page-/sequence-sharded pools')
    from jax.sharding import PartitionSpec as P

    from skypilot_tpu.parallel import sharding as sharding_lib
    quant = key_scale is not None
    head_spec = P(None, _TENSOR_AXIS, None, None)
    in_specs = [head_spec, head_spec, head_spec]   # q + K/V pools
    if quant:
        in_specs += [head_spec, head_spec]         # scale pools
    in_specs += [P(), P()]                         # table, mask
    out_spec = P(None, None, _TENSOR_AXIS, None)   # [B, S, H, d]

    def _shard(q_, pk, pv, *rest):
        if quant:
            ks, vs, tbl, msk = rest
        else:
            ks = vs = None
            tbl, msk = rest
        return _paged_decode_attention_impl(
            q_, pk, pv, tbl, msk, scale=scale,
            probs_dtype=probs_dtype, key_scale=ks, value_scale=vs,
            interpret=interpret)

    args = [q, page_key, page_value]
    if quant:
        args += [key_scale, value_scale]
    args += [table, mask]
    wrapped = sharding_lib.shard_map_compat(
        _shard, mesh=mesh, in_specs=tuple(in_specs),
        out_specs=out_spec, axis_names=frozenset({_TENSOR_AXIS}))
    return wrapped(*args)


def _paged_decode_attention_impl(q: jax.Array, page_key: jax.Array,
                                 page_value: jax.Array,
                                 table: jax.Array,
                                 mask: jax.Array, *, scale: float,
                                 probs_dtype: Any,
                                 key_scale: Optional[jax.Array] = None,
                                 value_scale: Optional[jax.Array]
                                 = None,
                                 interpret: Optional[bool] = None
                                 ) -> jax.Array:
    """Single-shard pallas_call: decode attention over (a local shard
    of) the paged KV pools.

    q:          [B, H, S, d] float queries (S = 1 decode, S = k+1
                speculative verify).
    page_key /
    page_value: [n_pages, kvh, page_size, d] physical pools (bf16/f32,
                or int8 with the sibling scale pools below).
    table:      [B, n_read] int32 — each row's block table truncated to
                the pages under the bucketed read window.  Entries a
                row never allocated point at the reserved null page 0;
                `mask` hides their content.
    mask:       bool [B, 1, S|1, n_read*page_size] — the visibility the
                XLA path computes (revealed slots + verify windows +
                sliding window + null-page masking), broadcast over kv
                heads and the head group inside the kernel.
    key_scale /
    value_scale: [n_pages, kvh, page_size, 1] f32 absmax scale pools
                for int8 K/V (both or neither).
    interpret:  None = `not _on_tpu()` (interpreter mode off-TPU for
                tests; compiled Mosaic on TPU).

    Returns [B, S, H, d] in `probs_dtype` — same contract as
    `grouped_attention` / `quantized_grouped_attention`.
    """
    b, h, s, d = q.shape
    n_pages, kvh, ps, dp = page_key.shape
    if h % kvh:
        raise ValueError(
            f'query heads ({h}) not divisible by kv heads ({kvh})')
    if dp != d:
        raise ValueError(
            f'pool head_dim ({dp}) != query head_dim ({d})')
    quant = key_scale is not None
    if quant != (value_scale is not None):
        raise ValueError('key_scale and value_scale must be passed '
                         'together (int8 pools) or not at all')
    group = h // kvh
    gs = group * s
    n_read = table.shape[1]
    read_len = n_read * ps
    # [B, H, S, d] -> [B, kvh, G*S, d]: the same head order the grouped
    # einsum uses (head index = kv_head * G + group member).
    qg = q.reshape(b, kvh, gs, d)
    # [B, 1, S|1, read_len] -> [B, S, read_len] (kv-head axis is
    # broadcast; a [B,1,1,L] decode mask broadcasts over S=1 queries).
    mask3 = jnp.broadcast_to(mask[:, 0], (b, s, read_len))

    def tile(index_map, block):
        return pl.BlockSpec(block, index_map)

    pool_spec = tile(lambda bi, hi, j, tbl: (tbl[bi, j], hi, 0, 0),
                     (1, 1, ps, d))
    in_specs = [
        tile(lambda bi, hi, j, tbl: (bi, hi, 0, 0), (1, 1, gs, d)),
        pool_spec,
        pool_spec,
    ]
    args = [qg, page_key, page_value]
    if quant:
        scale_spec = tile(
            lambda bi, hi, j, tbl: (tbl[bi, j], hi, 0, 0),
            (1, 1, ps, 1))
        in_specs += [scale_spec, scale_spec]
        args += [key_scale, value_scale]
    in_specs.append(tile(lambda bi, hi, j, tbl: (bi, 0, j),
                         (1, s, ps)))
    args.append(mask3)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, kvh, n_read),
        in_specs=in_specs,
        out_specs=tile(lambda bi, hi, j, tbl: (bi, hi, 0, 0),
                       (1, 1, gs, d)),
        scratch_shapes=[
            pltpu.VMEM((gs, 128), jnp.float32),    # running max
            pltpu.VMEM((gs, 128), jnp.float32),    # running denom
            pltpu.VMEM((gs, d), jnp.float32),      # output acc
        ],
    )

    def kernel(*refs):
        _decode_kernel_body(refs, scale=scale, group=group, s=s,
                            quant=quant)

    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kvh, gs, d), probs_dtype),
        interpret=(not _on_tpu()) if interpret is None else interpret,
    )(table, *args)
    # [B, kvh, G*S, d] -> [B, S, H, d] (grouped_attention's contract).
    return out.reshape(b, kvh, group, s, d).transpose(
        0, 3, 1, 2, 4).reshape(b, s, h, d)
