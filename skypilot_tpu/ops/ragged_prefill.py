"""Fused ragged-prefill attention kernel for TPU (Pallas).

The XLA chunked-prefill path (models/llama.py `run_cached_attention`,
global-cursor branch) writes a chunk's K/V at the cache cursor and then
slices the live prefix — `cached_k.value[:, :, :read_len]` — for the
grouped epilogue.  XLA materializes that slice as a contiguous
[B, kvh, read_len, hd] copy (plus the V and int8-scale siblings): an
HBM round-trip that is written and immediately re-read every chunk,
growing with the prompt's live prefix — the prefill twin of the decode
gather `ops/paged_attention.py` killed in PR 12.

This kernel streams the prefix straight from the cache instead.  The
cache is viewed as LOGICAL pages of `page_size` positions and a block
table rides in as a scalar-prefetch operand — the same
`PrefetchScalarGridSpec` indirection the fused decode kernel uses —
so each (row, kv-head, logical-page) program's K/V BlockSpec index map
dereferences `(b, h, table[b, j], 0)` and one [page_size, d] tile
streams cache -> VMEM per grid step.  For the contiguous prefill cache
the table is the identity (logical page j at position j*ps); the
indirection is kept so prefix-shared pages hydrated from the pool
stream once through the same mechanism.  Fused in one program, with
zero intermediate HBM tensors:

  - prefix streaming (the BlockSpec indirection above; no sliced copy);
  - causal masking against the chunk's cache-cursor base, computed
    in-kernel from a second scalar-prefetch operand (never a
    [S, read_len] mask tensor in HBM);
  - the optional sliding window and the kv_mask validity row, the
    latter sliced per page by its own BlockSpec;
  - int8 dequant: per-(kv-head, position) f32 scales fold into the
    dots (key scales scale the score columns post-QK, value scales
    fold into the probabilities pre-PV) — no float copy of the cache;
  - grouped attention: the G = H/kvh query heads sharing a kv head
    ride one program as a [G*S, d] q block, S = the chunk length;
  - online-softmax accumulation across the prefix's pages (the
    f32 m/l/acc tiling from ops/flash_attention.py's fwd kernel).

Off-TPU the kernel runs in interpreter mode (tests); serving defaults
never select it off-TPU — the XLA slice path stays the production
fallback and parity oracle (see `--prefill-kernel` on the engine).
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from skypilot_tpu.parallel import mesh as mesh_lib

_NEG_INF = -1e30
_TENSOR_AXIS = mesh_lib.AXIS_TENSOR


def _on_tpu() -> bool:
    return jax.default_backend() == 'tpu'


def _prefill_kernel_body(refs, *, scale: float, group: int, s: int,
                         ps: int, window: Optional[int],
                         quant: bool) -> None:
    """One grid step: fold logical page j of row b / kv-head h into the
    running online-softmax state.  Grid is (B, kvh, n_read) with the
    page axis innermost, so the o/scratch blocks stay VMEM-resident
    across a row's whole page sweep (the Pallas revisiting rule).

    Visibility is computed IN-KERNEL: query row r is chunk position
    i = r % s (the q block is [G, S] flattened group-major), its cache
    position is base + i, and page j covers cache positions
    [table[b, j]*ps, table[b, j]*ps + ps) — causal keeps kv_pos <=
    qpos, the sliding window keeps kv_pos >= qpos - window + 1, and
    the kv_mask page slice hides padding.  A page fully masked for
    some query contributes p = exp(0) garbage that the next unmasked
    page's correction factor exp(-1e30 - m) == 0 cancels exactly —
    the same self-correcting flash recurrence the decode kernel uses.
    """
    if quant:
        (tbl_ref, base_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
         kvm_ref, o_ref, m_ref, l_ref, acc_ref) = refs
    else:
        (tbl_ref, base_ref, q_ref, k_ref, v_ref,
         kvm_ref, o_ref, m_ref, l_ref, acc_ref) = refs
    bi = pl.program_id(0)
    j = pl.program_id(2)
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q = q_ref[0, 0].astype(jnp.float32)            # [G*S, d]
    k = k_ref[0, 0].astype(jnp.float32)            # [ps, d]
    v = v_ref[0, 0].astype(jnp.float32)            # [ps, d]
    gs = q.shape[0]
    sc = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale   # [G*S, ps]
    if quant:
        sc = sc * ks_ref[0, 0][:, 0][None, :]
    # In-kernel ragged causal mask against the cache-cursor base.
    row = jax.lax.broadcasted_iota(jnp.int32, (gs, ps), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (gs, ps), 1)
    qpos = base_ref[bi] + jax.lax.rem(row, s)
    kv_pos = tbl_ref[bi, j] * ps + col
    keep = kv_pos <= qpos
    if window is not None:
        keep &= kv_pos >= qpos - window + 1
    keep &= kvm_ref[0][None, :]
    sc = jnp.where(keep, sc, _NEG_INF)
    m_prev = m_ref[:, :1]                          # [G*S, 1]
    m_cur = jnp.max(sc, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(sc - m_new)                        # [G*S, ps]
    correction = jnp.exp(m_prev - m_new)
    l_new = correction * l_ref[:, :1] + jnp.sum(p, axis=1,
                                                keepdims=True)
    if quant:
        p = p * vs_ref[0, 0][:, 0][None, :]
    acc_ref[:] = acc_ref[:] * correction + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nj - 1)
    def _finalize():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


def ragged_prefill_attention(q: jax.Array, keys: jax.Array,
                             values: jax.Array, table: jax.Array,
                             base: jax.Array, kv_mask: jax.Array, *,
                             scale: float, probs_dtype: Any,
                             page_size: int,
                             window: Optional[int] = None,
                             key_scale: Optional[jax.Array] = None,
                             value_scale: Optional[jax.Array] = None,
                             interpret: Optional[bool] = None
                             ) -> jax.Array:
    """Chunked-prefill attention straight from the contiguous cache.

    Under an ambient mesh with `tensor > 1` the kernel self-lowers
    through shard_map manual over the tensor axis, exactly like the
    fused decode kernel: each chip streams its LOCAL kv-head shard of
    the cache, q's head axis splits into the same contiguous
    kv-head-major chunks, the table/base/kv_mask ride in whole, and
    the [B, S, H, d] output stays head-sharded for the downstream
    o_proj row-parallel psum.  No collective runs inside the kernel.
    """
    mesh = None
    from skypilot_tpu.ops import paged_attention as pa
    if not pa._in_manual_region(_TENSOR_AXIS):
        from skypilot_tpu.parallel import sharding as sharding_lib
        mesh = sharding_lib.ambient_physical_mesh()
    tensor = mesh.shape.get(_TENSOR_AXIS, 1) if mesh is not None else 1
    if tensor <= 1:
        return _ragged_prefill_impl(
            q, keys, values, table, base, kv_mask, scale=scale,
            probs_dtype=probs_dtype, page_size=page_size,
            window=window, key_scale=key_scale,
            value_scale=value_scale, interpret=interpret)
    kvh = keys.shape[1]
    if kvh % tensor:
        # resolve_kernels refuses this combination at startup; raising
        # here too turns any path that slips through into a
        # diagnosable error instead of a Pallas partitioning crash.
        raise ValueError(
            f'fused ragged prefill under tensor={tensor} needs the '
            f'cache kv-head axis ({kvh}) divisible by it; this '
            "geometry must use prefill_kernel='xla'")
    from jax.sharding import PartitionSpec as P

    from skypilot_tpu.parallel import sharding as sharding_lib
    quant = key_scale is not None
    head_spec = P(None, _TENSOR_AXIS, None, None)
    in_specs = [head_spec, head_spec, head_spec]   # q + K/V caches
    if quant:
        in_specs += [head_spec, head_spec]         # scale caches
    in_specs += [P(), P(), P()]                    # table, base, mask
    out_spec = P(None, None, _TENSOR_AXIS, None)   # [B, S, H, d]

    def _shard(q_, ck, cv, *rest):
        if quant:
            ks, vs, tbl, bs, msk = rest
        else:
            ks = vs = None
            tbl, bs, msk = rest
        return _ragged_prefill_impl(
            q_, ck, cv, tbl, bs, msk, scale=scale,
            probs_dtype=probs_dtype, page_size=page_size,
            window=window, key_scale=ks, value_scale=vs,
            interpret=interpret)

    args = [q, keys, values]
    if quant:
        args += [key_scale, value_scale]
    args += [table, base, kv_mask]
    wrapped = sharding_lib.shard_map_compat(
        _shard, mesh=mesh, in_specs=tuple(in_specs),
        out_specs=out_spec, axis_names=frozenset({_TENSOR_AXIS}))
    return wrapped(*args)


def _ragged_prefill_impl(q: jax.Array, keys: jax.Array,
                         values: jax.Array, table: jax.Array,
                         base: jax.Array, kv_mask: jax.Array, *,
                         scale: float, probs_dtype: Any,
                         page_size: int,
                         window: Optional[int] = None,
                         key_scale: Optional[jax.Array] = None,
                         value_scale: Optional[jax.Array] = None,
                         interpret: Optional[bool] = None
                         ) -> jax.Array:
    """Single-shard pallas_call: one prefill chunk's attention over (a
    local shard of) the contiguous cache.

    q:          [B, H, S, d] float chunk queries (S = chunk length;
                query i sits at cache position base + i).
    keys /
    values:     [B, kvh, L, d] contiguous cache (bf16/f32, or int8
                with the sibling scale leaves below).  L % page_size
                must be 0; the kernel reads it as L//page_size logical
                pages.
    table:      [B, n_read] int32 — each row's logical-page walk,
                truncated to the pages under the bucketed read window.
                Identity (page j at slot j) for the contiguous prefill
                cache; kept general so hydrated prefix pages stream
                through the same scalar-prefetch indirection.
    base:       int32 scalar or [B] — each row's cache-cursor base:
                causal visibility is kv_pos <= base[b] + i per query
                i, computed in-kernel (no mask tensor in HBM).  A
                scalar broadcasts (the batch-1 staging prefill).
    kv_mask:    bool [B, L] — validity row (padding/unwritten slots);
                sliced per logical page by its BlockSpec.
    key_scale /
    value_scale: [B, kvh, L, 1] f32 absmax scales for int8 K/V (both
                or neither).
    interpret:  None = `not _on_tpu()` (interpreter mode off-TPU for
                tests; compiled Mosaic on TPU).

    Returns [B, S, H, d] in `probs_dtype` — the same contract as
    `grouped_attention` and the XLA chunked-prefill epilogue.
    """
    b, h, s, d = q.shape
    bk, kvh, max_len, dk = keys.shape
    ps = page_size
    if ps <= 0:
        raise ValueError(f'page_size must be > 0, got {ps}')
    if max_len % ps:
        raise ValueError(
            f'cache length ({max_len}) must be a multiple of '
            f'page_size ({ps})')
    if h % kvh:
        raise ValueError(
            f'query heads ({h}) not divisible by kv heads ({kvh})')
    if dk != d:
        raise ValueError(
            f'cache head_dim ({dk}) != query head_dim ({d})')
    quant = key_scale is not None
    if quant != (value_scale is not None):
        raise ValueError('key_scale and value_scale must be passed '
                         'together (int8 cache) or not at all')
    group = h // kvh
    gs = group * s
    n_read = table.shape[1]
    if n_read * ps > max_len:
        raise ValueError(
            f'table walks {n_read} pages of {ps} positions, beyond '
            f'the cache length ({max_len})')
    base = jnp.broadcast_to(
        jnp.asarray(base, jnp.int32).reshape(-1), (b,))
    # [B, H, S, d] -> [B, kvh, G*S, d]: the same head order the grouped
    # einsum uses (head index = kv_head * G + group member).
    qg = q.reshape(b, kvh, gs, d)

    def tile(index_map, block):
        return pl.BlockSpec(block, index_map)

    cache_spec = tile(
        lambda bi, hi, j, tbl, bs: (bi, hi, tbl[bi, j], 0),
        (1, 1, ps, d))
    in_specs = [
        tile(lambda bi, hi, j, tbl, bs: (bi, hi, 0, 0), (1, 1, gs, d)),
        cache_spec,
        cache_spec,
    ]
    args = [qg, keys, values]
    if quant:
        scale_spec = tile(
            lambda bi, hi, j, tbl, bs: (bi, hi, tbl[bi, j], 0),
            (1, 1, ps, 1))
        in_specs += [scale_spec, scale_spec]
        args += [key_scale, value_scale]
    in_specs.append(tile(lambda bi, hi, j, tbl, bs: (bi, tbl[bi, j]),
                         (1, ps)))
    args.append(kv_mask)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, kvh, n_read),
        in_specs=in_specs,
        out_specs=tile(lambda bi, hi, j, tbl, bs: (bi, hi, 0, 0),
                       (1, 1, gs, d)),
        scratch_shapes=[
            pltpu.VMEM((gs, 128), jnp.float32),    # running max
            pltpu.VMEM((gs, 128), jnp.float32),    # running denom
            pltpu.VMEM((gs, d), jnp.float32),      # output acc
        ],
    )

    def kernel(*refs):
        _prefill_kernel_body(refs, scale=scale, group=group, s=s,
                             ps=ps, window=window, quant=quant)

    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kvh, gs, d), probs_dtype),
        interpret=(not _on_tpu()) if interpret is None else interpret,
    )(table, base, *args)
    # [B, kvh, G*S, d] -> [B, S, H, d] (grouped_attention's contract).
    return out.reshape(b, kvh, group, s, d).transpose(
        0, 3, 1, 2, 4).reshape(b, s, h, d)
