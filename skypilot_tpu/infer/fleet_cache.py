"""Host-RAM prefix-page tier + fleet peer fetch for the paged KV cache.

The device page pool is small and hot: under memory pressure the
allocator cannibalises reclaimable prefix pages and their KV is gone —
the next request paying a full re-prefill for tokens the fleet already
computed.  This module adds the two cheaper tiers in between:

- **Host tier** (`HostPrefixCache`): a bounded LRU of spilled pages in
  host RAM, keyed by the allocator's chain hashes.  The allocator's
  spill hook copies a page here right before its device copy is
  cannibalised (or prefers victims that already have a copy); a later
  prefix hit rehydrates the device page from host RAM in microseconds
  instead of re-running prefill.
- **Fleet tier** (`fetch_prefix_from_peer`): a replica that misses
  locally asks the rendezvous-hash OWNER of the prefix (the router
  names it in the `X-Skytpu-Prefix-Peer` header) for its spilled pages
  over `GET /kv_prefix`, shipped in the SKHO kv_prefix framing.  The
  fetched pages land in the LOCAL host tier, and the single
  rehydration path in the engine does the rest — scale-up replicas
  warm from survivors instead of from zero.

Thread-safety: unlike the allocator (single scheduler thread), this
cache is touched from HTTP handler threads too — `/kv_prefix` serves
from it and peer fetches populate it — so it owns exactly one lock,
held only around dict/byte bookkeeping, never across a device or
network call (flat lock hierarchy; see docs/architecture.md).

numpy + stdlib only; no jax import.  The engine hands us host arrays
(already device_get'd) and uploads them back itself.
"""
from __future__ import annotations

import collections
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from skypilot_tpu.infer import handoff

# Default peer-fetch deadline.  A prefix fetch is an optimisation —
# losing the race must never stall admission longer than a short
# prefill would have.
FETCH_TIMEOUT_S = 5.0


class HostPrefixCache:
    """Bounded LRU of spilled KV pages in host RAM.

    One entry per chain hash: a dict of pool-leaf name (e.g.
    'page_key', 'page_value_scale') -> that page's host array.  Entry
    size is the sum of leaf nbytes; inserting past `max_bytes` evicts
    least-recently-USED entries (get() refreshes recency, has() does
    not — the allocator's victim scan must not perturb LRU order).
    """

    def __init__(self, max_bytes: int):
        if max_bytes <= 0:
            raise ValueError(f'max_bytes must be > 0, got {max_bytes}')
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._pages: 'collections.OrderedDict[int, Dict[str, np.ndarray]]' \
            = collections.OrderedDict()
        self._nbytes: Dict[int, int] = {}
        self._bytes = 0
        # Lifetime counters; the engine's telemetry publisher diffs
        # them per step into the skytpu_fleet_cache_* series.
        self.hits_total = 0
        self.misses_total = 0
        self.inserted_pages_total = 0
        self.inserted_bytes_total = 0
        self.evicted_pages_total = 0

    @staticmethod
    def _entry_bytes(leaves: Dict[str, np.ndarray]) -> int:
        return sum(int(a.nbytes) for a in leaves.values())

    # Lock-free reads for the engine's per-step telemetry publisher
    # (torn reads are fine — gauges re-converge next step; taking the
    # lock on the decode hot path is not).
    @property
    def stored_bytes(self) -> int:
        return self._bytes

    @property
    def stored_pages(self) -> int:
        return len(self._pages)

    def put(self, h: int, leaves: Dict[str, np.ndarray]) -> bool:
        """Store one page's leaves under chain hash `h` (arrays are
        kept by reference — callers hand over host copies they no
        longer mutate).  Returns False when the single page exceeds the
        whole budget (nothing stored); otherwise evicts LRU entries
        until it fits."""
        size = self._entry_bytes(leaves)
        if size > self.max_bytes:
            return False
        with self._lock:
            old = self._nbytes.pop(h, None)
            if old is not None:
                del self._pages[h]
                self._bytes -= old
            while self._bytes + size > self.max_bytes and self._pages:
                victim, _ = self._pages.popitem(last=False)
                self._bytes -= self._nbytes.pop(victim)
                self.evicted_pages_total += 1
            self._pages[h] = leaves
            self._nbytes[h] = size
            self._bytes += size
            self.inserted_pages_total += 1
            self.inserted_bytes_total += size
        return True

    def get(self, h: int) -> Optional[Dict[str, np.ndarray]]:
        """The page's leaves, refreshing LRU recency; None on miss."""
        with self._lock:
            leaves = self._pages.get(h)
            if leaves is None:
                self.misses_total += 1
                return None
            self._pages.move_to_end(h)
            self.hits_total += 1
            return leaves

    def has(self, h: int) -> bool:
        """Presence check WITHOUT touching LRU order or counters —
        the allocator's victim scan calls this per candidate."""
        with self._lock:
            return h in self._pages

    def discard(self, h: int) -> None:
        with self._lock:
            size = self._nbytes.pop(h, None)
            if size is not None:
                del self._pages[h]
                self._bytes -= size

    def snapshot_run(self, hashes: Sequence[int]
                     ) -> Tuple[List[int],
                                List[Dict[str, np.ndarray]]]:
        """Longest leading run of `hashes` present, as parallel
        (hashes, leaf-dicts) lists — what `GET /kv_prefix` serves.
        Stops at the first miss because a chain's later pages are
        useless without the earlier ones."""
        served_h: List[int] = []
        served_p: List[Dict[str, np.ndarray]] = []
        with self._lock:
            for h in hashes:
                leaves = self._pages.get(h)
                if leaves is None:
                    break
                self._pages.move_to_end(h)
                served_h.append(int(h))
                served_p.append(leaves)
            self.hits_total += len(served_h)
            if len(served_h) < len(hashes):
                self.misses_total += 1
        return served_h, served_p

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                'stored_pages': len(self._pages),
                'stored_bytes': self._bytes,
                'max_bytes': self.max_bytes,
                'hits_total': self.hits_total,
                'misses_total': self.misses_total,
                'inserted_pages_total': self.inserted_pages_total,
                'inserted_bytes_total': self.inserted_bytes_total,
                'evicted_pages_total': self.evicted_pages_total,
            }

    def clear(self) -> None:
        with self._lock:
            self._pages.clear()
            self._nbytes.clear()
            self._bytes = 0


def fetch_prefix_from_peer(peer_url: str, hashes: Sequence[int],
                           model: str, kv_cache_dtype: str,
                           page_size: int,
                           timeout: float = FETCH_TIMEOUT_S
                           ) -> List[Tuple[int, Dict[str, np.ndarray]]]:
    """Ask `peer_url`'s `GET /kv_prefix` for the leading run of
    `hashes` it holds in its host tier.  Returns [(hash, leaves)...]
    in chain order ([] on any failure — peer down, version skew,
    geometry mismatch: a fleet-tier miss is always survivable, the
    caller just prefills).  The arrays are copies (the response buffer
    is ours), safe to stash in a HostPrefixCache."""
    if not hashes:
        return []
    query = urllib.parse.urlencode({
        'hashes': ','.join(str(int(h)) for h in hashes),
    })
    url = f'{peer_url.rstrip("/")}/kv_prefix?{query}'
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            blob = resp.read()
        meta, tensors = handoff.deserialize_artifact(blob)
    except (urllib.error.URLError, OSError, TimeoutError,
            handoff.HandoffError):
        return []
    if meta.get('kind') != handoff.KIND_KV_PREFIX:
        return []
    if meta.get('model') != model \
            or meta.get('kv_cache_dtype') != kv_cache_dtype \
            or int(meta.get('page_size', -1)) != page_size:
        return []
    out: List[Tuple[int, Dict[str, np.ndarray]]] = []
    want = [int(h) for h in hashes]
    try:
        pages = handoff.split_kv_prefix(meta, tensors)
    except handoff.HandoffError:
        return []
    for i, (h, leaves) in enumerate(pages):
        # Trust only the leading run that matches what we asked for.
        if i >= len(want) or h != want[i] or not leaves:
            break
        out.append((h, {name: np.array(arr, copy=True)
                        for name, arr in leaves.items()}))
    return out
