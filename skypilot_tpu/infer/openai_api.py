"""OpenAI-compatible protocol: request parsing + response framing.

The reference's LLM recipes all serve the OpenAI API through vLLM
(`llm/qwen/qwen25-7b.yaml:30-33`); this framework owns its engine, so
it owns the protocol layer too.  Pure functions here — the HTTP/SSE
transport lives in server.py, which keeps every framing rule unit-
testable without sockets.

Supported: /v1/completions and /v1/chat/completions (stream and
non-stream), stop sequences, max_tokens/temperature/top_p/top_k/seed,
usage accounting.  Unsupported fields (n>1, logprobs, tools) raise
OpenAIError with an OpenAI-style error body.
"""
from __future__ import annotations

import dataclasses
import time
import uuid
from typing import Any, Dict, List, Optional


class OpenAIError(ValueError):
    """Maps to an OpenAI-style error JSON with an HTTP status."""

    def __init__(self, message: str, status: int = 400,
                 err_type: str = 'invalid_request_error'):
        super().__init__(message)
        self.status = status
        self.err_type = err_type

    def body(self) -> Dict[str, Any]:
        return {'error': {'message': str(self), 'type': self.err_type,
                          'param': None, 'code': None}}


@dataclasses.dataclass
class ParsedRequest:
    """One generation request, normalized from either endpoint."""
    prompt_text: str
    max_tokens: int
    temperature: float
    top_p: float
    top_k: int
    seed: Optional[int]
    stream: bool
    stop: List[str]
    model: str
    chat: bool
    request_id: str = dataclasses.field(
        default_factory=lambda: uuid.uuid4().hex[:24])
    created: int = dataclasses.field(
        default_factory=lambda: int(time.time()))

    @property
    def oai_id(self) -> str:
        prefix = 'chatcmpl' if self.chat else 'cmpl'
        return f'{prefix}-{self.request_id}'


def _parse_stop(raw: Any) -> List[str]:
    if raw is None:
        return []
    if isinstance(raw, str):
        return [raw]
    if isinstance(raw, list) and all(isinstance(s, str) for s in raw):
        if len(raw) > 4:
            raise OpenAIError('stop: at most 4 sequences')
        return raw
    raise OpenAIError('stop must be a string or list of strings')


def _get(payload: Dict[str, Any], key: str, default: Any) -> Any:
    """Explicit JSON null means use-the-default (OpenAI semantics;
    several client libraries serialize unset fields as nulls)."""
    value = payload.get(key)
    return default if value is None else value


def _common_fields(payload: Dict[str, Any], default_model: str):
    try:
        if int(_get(payload, 'n', 1)) != 1:
            raise OpenAIError('n > 1 is not supported')
        if payload.get('logprobs'):
            raise OpenAIError('logprobs is not supported')
        max_tokens = int(_get(payload, 'max_tokens',
                              _get(payload, 'max_completion_tokens',
                                   16)))
        if max_tokens < 1:
            raise OpenAIError('max_tokens must be >= 1')
        seed = payload.get('seed')
        return dict(
            max_tokens=max_tokens,
            temperature=float(_get(payload, 'temperature', 1.0)),
            top_p=float(_get(payload, 'top_p', 1.0)),
            # top_k: extension (vLLM has it)
            top_k=int(_get(payload, 'top_k', 0)),
            seed=int(seed) if seed is not None else None,
            stream=bool(payload.get('stream', False)),
            stop=_parse_stop(payload.get('stop')),
            model=str(payload.get('model') or default_model),
        )
    except (TypeError, ValueError) as e:
        if isinstance(e, OpenAIError):
            raise
        raise OpenAIError(f'malformed request field: {e}') from e


def parse_completion_request(payload: Dict[str, Any],
                             default_model: str) -> ParsedRequest:
    prompt = payload.get('prompt')
    if isinstance(prompt, list):
        if len(prompt) != 1 or not isinstance(prompt[0], str):
            raise OpenAIError(
                'prompt must be a string (or a 1-element list)')
        prompt = prompt[0]
    if not isinstance(prompt, str) or not prompt:
        raise OpenAIError('prompt must be a non-empty string')
    return ParsedRequest(prompt_text=prompt, chat=False,
                         **_common_fields(payload, default_model))


def render_chat_prompt(messages: List[Dict[str, Any]]) -> str:
    """Minimal generic chat template (model-family templates belong
    to real checkpoints' HF tokenizers; this is the fallback)."""
    lines = []
    for m in messages:
        role, content = m.get('role'), m.get('content')
        if role not in ('system', 'user', 'assistant') or \
                not isinstance(content, str):
            raise OpenAIError(
                'each message needs a role in '
                "('system','user','assistant') and string content")
        lines.append(f'{role}: {content}')
    lines.append('assistant:')
    return '\n'.join(lines)


def parse_chat_request(payload: Dict[str, Any],
                       default_model: str) -> ParsedRequest:
    messages = payload.get('messages')
    if not isinstance(messages, list) or not messages:
        raise OpenAIError('messages must be a non-empty list')
    return ParsedRequest(prompt_text=render_chat_prompt(messages),
                         chat=True,
                         **_common_fields(payload, default_model))


class StopScanner:
    """Cuts the output at the earliest stop sequence across chunk
    boundaries: emitted text never contains any part of a stop, and a
    stop split across two decode steps is still caught."""

    def __init__(self, stops: List[str]):
        self._stops = [s for s in stops if s]
        self._held = ''  # tail that could be a stop prefix
        self.hit = False

    def _longest_holdback(self, text: str) -> int:
        n = 0
        for stop in self._stops:
            for k in range(min(len(stop) - 1, len(text)), 0, -1):
                if text.endswith(stop[:k]):
                    n = max(n, k)
                    break
        return n

    def feed(self, chunk: str) -> str:
        """Safe-to-emit text from this chunk ('' after a stop hit)."""
        if self.hit or not self._stops:
            return '' if self.hit else chunk
        text = self._held + chunk
        cut = None
        for stop in self._stops:
            idx = text.find(stop)
            if idx != -1 and (cut is None or idx < cut):
                cut = idx
        if cut is not None:
            self.hit = True
            self._held = ''
            return text[:cut]
        hold = self._longest_holdback(text)
        self._held = text[len(text) - hold:] if hold else ''
        return text[:len(text) - hold] if hold else text

    def flush(self) -> str:
        """Pending holdback at end-of-generation (no stop ever hit)."""
        out, self._held = self._held, ''
        return '' if self.hit else out


def completion_response(req: ParsedRequest, text: str,
                        finish_reason: str, prompt_tokens: int,
                        completion_tokens: int) -> Dict[str, Any]:
    usage = {'prompt_tokens': prompt_tokens,
             'completion_tokens': completion_tokens,
             'total_tokens': prompt_tokens + completion_tokens}
    if req.chat:
        return {
            'id': req.oai_id, 'object': 'chat.completion',
            'created': req.created, 'model': req.model,
            'choices': [{'index': 0,
                         'message': {'role': 'assistant',
                                     'content': text},
                         'finish_reason': finish_reason}],
            'usage': usage,
        }
    return {
        'id': req.oai_id, 'object': 'text_completion',
        'created': req.created, 'model': req.model,
        'choices': [{'index': 0, 'text': text, 'logprobs': None,
                     'finish_reason': finish_reason}],
        'usage': usage,
    }


def stream_chunk(req: ParsedRequest, text: Optional[str],
                 finish_reason: Optional[str] = None,
                 first: bool = False) -> Dict[str, Any]:
    """One SSE data event.  Chat streams send role on the first chunk
    and content deltas after; completion streams send text deltas."""
    if req.chat:
        delta: Dict[str, Any] = {}
        if first:
            delta['role'] = 'assistant'
        if text:
            delta['content'] = text
        choice = {'index': 0, 'delta': delta,
                  'finish_reason': finish_reason}
        obj = 'chat.completion.chunk'
    else:
        choice = {'index': 0, 'text': text or '', 'logprobs': None,
                  'finish_reason': finish_reason}
        obj = 'text_completion'
    return {'id': req.oai_id, 'object': obj, 'created': req.created,
            'model': req.model, 'choices': [choice]}
