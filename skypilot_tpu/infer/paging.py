"""Host-side page allocator for the paged KV cache.

The device holds a flat pool of `n_pages` KV pages (page 0 is a
reserved null page — dead slot rows and redirected writes land there
and are never read).  This allocator owns the host-side bookkeeping:

- a free stack of page ids,
- per-page refcounts (a page may back several slots at once when it
  holds a shared prompt prefix),
- a prefix map from a chain hash of page-aligned prompt token chunks
  to the page holding that chunk's K/V, so N concurrent requests with
  a common system prompt prefill it once and read it once,
- an LRU pool of "reclaimable" pages: prefix pages whose refcount
  dropped to zero keep their contents and stay matchable until the
  free stack runs dry, at which point `alloc` cannibalises them
  oldest-first (RadixAttention-style eviction, flattened to a chain).

Chain hashing: page i of a prompt hashes `(hash_of_page_{i-1},
tuple(tokens[i*ps:(i+1)*ps]))`.  Because prefill attention is causal,
a page's K/V depend only on the tokens at and before it — two prompts
agreeing on the first k*ps tokens produce byte-identical first k
pages, which is exactly what the chain hash certifies.

Pure host-side Python; no jax imports.  Thread-unsafe by design: the
engine calls it only from its single scheduler thread.

Lock hierarchy note: this lock-free allocator is one instance of the
serving stack's global locking discipline, which the skylint
`lock-order-discipline` rule derived from the tree and now enforces —
the hierarchy is deliberately FLAT.  One lock per component
(engine `_submit_lock`, server `_lock`/`_drain_lock`, router/breaker/
policy/supervisor `_lock`s, observability buffer `_lock`s), and no
code path acquires a second lock while holding one, directly or
through any call chain; cross-component calls release first.  The
full table lives in docs/architecture.md ("Lock acquisition
hierarchy").  Adding a nested acquire anywhere is how the first half
of a deadlock starts, and the linter will flag it.

Tensor parallelism never reaches this layer: under a `tensor=N` mesh
the engine shards the device pools on the KV-HEAD axis (every chip
holds page i's slice of its local heads), so page ids, refcounts,
prefix chains, and block tables stay GLOBAL — one allocator, one
replicated block table, N pool shards (engine._cache_sharding).
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, List, Optional, Sequence

from skypilot_tpu.utils import chaos

NULL_PAGE = 0


def chain_hashes(tokens: Sequence[int], page_size: int) -> List[int]:
    """Chain hash of each full page-aligned chunk of ``tokens``.

    ``hashes[i]`` commits to tokens[:(i+1)*page_size] (causal prefill
    makes a page's K/V a pure function of the tokens at and before it).
    Stable across processes for integer token ids: int and
    tuple-of-int hashing does not depend on ``PYTHONHASHSEED``, so a
    router process and its replica processes compute identical chains.
    """
    hashes: List[int] = []
    h = 0
    for i in range(len(tokens) // page_size):
        h = hash((h, tuple(tokens[i * page_size:(i + 1) * page_size])))
        hashes.append(h)
    return hashes


def routing_key(tokens: Sequence[int], page_size: int) -> int:
    """Prefix-affinity routing key for a prompt: the chain hash of its
    FIRST page (requests sharing a page-aligned prefix share it — the
    granularity at which a replica's prefix cache can help), or a
    direct hash of the whole short prompt when it fills no page.  The
    router keys replica affinity off this so prompts that would share
    prefix pages land on the replica already holding them."""
    hashes = chain_hashes(tokens, page_size)
    if hashes:
        return hashes[0]
    return hash((0, tuple(tokens)))


class PageAllocator:
    """Free list + refcounts + prefix-chain map over a fixed page pool."""

    def __init__(self, n_pages: int, page_size: int):
        if n_pages < 2:
            raise ValueError(
                f'n_pages must be >= 2 (page {NULL_PAGE} is reserved), '
                f'got {n_pages}')
        if page_size < 1:
            raise ValueError(f'page_size must be >= 1, got {page_size}')
        self.n_pages = n_pages
        self.page_size = page_size
        # LIFO so tests see deterministic low-page-first allocation.
        self._free: List[int] = list(range(n_pages - 1, 0, -1))
        self._ref: Dict[int, int] = {}
        # chain hash -> page holding that prefix chunk's K/V.
        self._prefix_page: Dict[int, int] = {}
        # page -> its chain hash (only registered prefix pages).
        self._page_hash: Dict[int, int] = {}
        # ref==0 registered pages, insertion order == LRU order.
        self._reclaimable: 'collections.OrderedDict[int, int]' = \
            collections.OrderedDict()
        # Lifetime count of reclaimable pages cannibalised by alloc().
        # Plain int the engine's telemetry publisher diffs per step —
        # this module stays dependency-free (no metrics import).
        self.cannibalized_total = 0
        # Lifetime count of pages copied to the host-RAM spill tier
        # before their device copy was cannibalised (same diff
        # pattern as cannibalized_total).
        self.spilled_total = 0
        # Host-RAM spill tier hooks (infer/fleet_cache.py), installed
        # by the engine when a host cache is configured.  `_spill_fn`
        # copies a device page's contents to host RAM keyed by its
        # chain hash; `_has_spill` says whether a hash already has a
        # host copy.  Unset (the default) leaves every code path in
        # this class byte-identical to the spill-free allocator.
        self._spill_fn: Optional[Callable[[int, int], None]] = None
        self._has_spill: Optional[Callable[[int], bool]] = None

    # -- capacity ---------------------------------------------------

    @property
    def capacity(self) -> int:
        """Usable pool size: every page a request could ever hold
        (page NULL_PAGE is reserved as the block-table sentinel)."""
        return self.n_pages - 1

    @property
    def free_pages(self) -> int:
        """Pages allocatable right now (fresh + reclaimable)."""
        return len(self._free) + len(self._reclaimable)

    @property
    def live_pages(self) -> int:
        """Pages currently referenced by at least one slot."""
        return len(self._ref)

    def refcount(self, page: int) -> int:
        return self._ref.get(page, 0)

    # -- alloc / retain / release -----------------------------------

    def alloc(self, n: int) -> Optional[List[int]]:
        """Take `n` pages with refcount 1 each, or None if they don't
        all fit (all-or-nothing, so admission never half-lands)."""
        if n < 0:
            raise ValueError(f'alloc({n})')
        if n > 0 and chaos.should_inject('alloc_exhaust'):
            return None
        if n > self.free_pages:
            return None
        out = []
        for _ in range(n):
            if self._free:
                page = self._free.pop()
            else:
                h, page = self._pick_victim()
                del self._reclaimable[h]
                del self._prefix_page[h]
                del self._page_hash[page]
                self.cannibalized_total += 1
            self._ref[page] = 1
            out.append(page)
        return out

    def _pick_victim(self) -> tuple:
        """Choose the reclaimable page to cannibalise.

        Preference order: the least-recently-released page that ALREADY
        has a host-RAM spill copy (its device contents are recoverable,
        so losing them costs a microsecond rehydrate, not a re-prefill);
        otherwise the LRU-oldest page, spilled to host RAM first when a
        spill tier is installed so the prefix stays recoverable.
        """
        if self._has_spill is not None:
            for h, page in self._reclaimable.items():
                if self._has_spill(h):
                    return h, page
        h, page = next(iter(self._reclaimable.items()))
        if self._spill_fn is not None:
            self._spill_fn(h, page)
            self.spilled_total += 1
        return h, page

    def set_spill_hooks(self,
                        spill_fn: Optional[Callable[[int, int], None]],
                        has_spill: Optional[Callable[[int], bool]]
                        ) -> None:
        """Install (or clear, with Nones) the host-RAM spill tier.
        `spill_fn(chain_hash, page)` must synchronously copy the device
        page's contents to host RAM; `has_spill(chain_hash)` reports an
        existing host copy.  Called once at engine construction, from
        the same single scheduler-thread discipline as everything else
        here."""
        self._spill_fn = spill_fn
        self._has_spill = has_spill

    def retain(self, page: int) -> None:
        """Add a reference (prefix hit).  Resurrects a reclaimable
        page — its contents are still valid until cannibalised."""
        ref = self._ref.get(page, 0)
        if ref == 0:
            h = self._page_hash.get(page)
            if h is None or h not in self._reclaimable:
                raise ValueError(f'retain of unallocated page {page}')
            del self._reclaimable[h]
        self._ref[page] = ref + 1

    def release(self, page: int) -> None:
        """Drop one reference.  At zero, registered prefix pages park
        in the reclaimable LRU (contents preserved); anonymous pages
        go straight back to the free stack."""
        ref = self._ref.get(page, 0)
        if ref <= 0:
            raise ValueError(f'release of unreferenced page {page}')
        if ref > 1:
            self._ref[page] = ref - 1
            return
        del self._ref[page]
        h = self._page_hash.get(page)
        if h is not None:
            self._reclaimable[h] = page
        else:
            self._free.append(page)

    # -- recovery ---------------------------------------------------

    def leak_report(self) -> Optional[str]:
        """None when every page is accounted for, else a description.

        After the engine releases all slot/prefill pages, the pool must
        be leak-free: no page referenced, and every non-null page on
        the free stack or parked in the reclaimable LRU.
        """
        problems = []
        if self._ref:
            sample = sorted(self._ref)[:4]
            problems.append(f'{len(self._ref)} page(s) still referenced '
                            f'(e.g. {sample})')
        missing = (self.n_pages - 1) - len(self._ref) \
            - len(self._free) - len(self._reclaimable)
        if missing:
            problems.append(f'{missing} page(s) unaccounted for')
        return '; '.join(problems) or None

    def reset(self) -> None:
        """Forget all references and prefix registrations.

        For post-failure recovery: the device pool is rebuilt from
        zeros, so cached prefix contents are gone and registrations
        must not survive.  ``cannibalized_total`` is a lifetime counter
        and is deliberately preserved.
        """
        self._free = list(range(self.n_pages - 1, 0, -1))
        self._ref.clear()
        self._prefix_page.clear()
        self._page_hash.clear()
        self._reclaimable.clear()

    # -- prefix sharing ---------------------------------------------

    def _chain_hashes(self, tokens: Sequence[int]) -> List[int]:
        return chain_hashes(tokens, self.page_size)

    def lookup_prefix(self, tokens: Sequence[int],
                      max_pages: Optional[int] = None) -> List[int]:
        """Longest already-cached page-aligned prefix of `tokens`.
        Every returned page is retained (caller must release)."""
        pages = []
        for i, h in enumerate(self._chain_hashes(tokens)):
            if max_pages is not None and i >= max_pages:
                break
            page = self._prefix_page.get(h)
            if page is None:
                break
            pages.append(page)
        for page in pages:
            self.retain(page)
        return pages

    def has_prefix(self, h: int) -> bool:
        """Whether chain hash `h` has a registered device page
        (referenced or reclaimable).  Advisory — HTTP handler threads
        use it to skip fleet fetches for locally resident pages; a
        stale answer costs one redundant fetch, never correctness."""
        return h in self._prefix_page

    def take_registered(self, h: int) -> Optional[int]:
        """Retained device page registered under chain hash `h`, or
        None.  Lets the rehydration walk resume on device-resident
        pages PAST a host-rehydrated gap — `lookup_prefix` stops at the
        first miss, but a chain can be device/host interleaved when a
        middle page was cannibalised."""
        page = self._prefix_page.get(h)
        if page is not None:
            self.retain(page)
        return page

    def adopt_prefix(self, h: int, page: int) -> bool:
        """Publish one rehydrated page (freshly alloc'd, contents just
        restored from the host tier) under its chain hash.  Refcount is
        untouched — the caller's alloc() reference becomes the slot's
        reference, and release() parks it back in the reclaimable LRU
        like any registered prefix page; there is exactly one owner per
        tier, so cross-tier double-free cannot arise.  Returns False
        (no-op) if the hash or page is already published."""
        if page == NULL_PAGE or page in self._page_hash \
                or h in self._prefix_page:
            return False
        self._prefix_page[h] = page
        self._page_hash[page] = h
        return True

    def register_prefix(self, tokens: Sequence[int],
                        pages: Sequence[int]) -> None:
        """Publish a prefilled prompt's pages for future sharing.
        `pages[i]` must hold the K/V of tokens[i*ps:(i+1)*ps]; only
        full pages are registrable, trailing tokens are ignored."""
        for i, h in enumerate(self._chain_hashes(tokens)):
            if i >= len(pages):
                break
            if h in self._prefix_page:
                continue                      # already published
            page = pages[i]
            if page in self._page_hash or page == NULL_PAGE:
                continue
            self._prefix_page[h] = page
            self._page_hash[page] = h
