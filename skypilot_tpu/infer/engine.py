"""KV-cache autoregressive inference engine (prefill/decode split).

JetStream-style serving loop, TPU-first:

  - **prefill**: one jitted full-prompt forward writes K/V into a
    static-shape cache [B, kv_heads, max_seq_len, head_dim] per layer
    (models/llama.py `_cached_attention`) — large matmuls, MXU-bound.
    Prompts are right-padded to bucket multiples so the set of compiled
    prefill shapes is small and the readiness warmup is honest;
  - **decode**: ONE jitted step per generated token that fuses
    sampling, the kv-mask slot write, and the forward — the host loop
    only fetches the sampled ids (needed for output/eos anyway);
  - ragged batches share one batch via the [B, max_seq_len] kv-mask, so
    rows of different lengths can't cross-contaminate (verified against
    cache-free re-forwarding in tests/unit_tests/test_infer.py);
  - params are served in bf16 by default (no optimizer here; f32 master
    weights are a training concern), sharded over a mesh when given,
    and loadable from a trainer Orbax checkpoint (the bucket-checkpoint
    contract, train/checkpoint.py).

The reference's serving path is an external vLLM container
(`llm/qwen/serve-110b.yaml` — SURVEY.md §2.11); this engine is the
framework-native replacement that SkyServe replicas run
(infer/server.py).
"""
from __future__ import annotations

import contextlib
import dataclasses
import functools
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

import skypilot_tpu.models as models_lib
from skypilot_tpu import sky_logging
from skypilot_tpu.parallel import sharding as sharding_lib

logger = sky_logging.init_logger(__name__)


@dataclasses.dataclass(frozen=True)
class SamplingConfig:
    temperature: float = 0.0     # 0 => greedy
    top_k: int = 0               # 0 => disabled
    top_p: float = 1.0           # 1 => disabled
    eos_id: Optional[int] = None
    max_new_tokens: int = 64


def sample_logits(logits: jax.Array, rng: jax.Array,
                  config: SamplingConfig) -> jax.Array:
    """Sample token ids [B] from logits [B, V]."""
    if config.temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / config.temperature
    if config.top_k > 0:
        kth = jax.lax.top_k(logits, config.top_k)[0][:, -1:]
        logits = jnp.where(logits < kth, -1e30, logits)
    if config.top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Smallest set of tokens whose mass exceeds top_p.
        cutoff_idx = jnp.sum(cum < config.top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -1e30, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def _cache_sharding(mesh, leaf) -> NamedSharding:
    """KV caches shard their kv-heads dim over `tensor` (matching the
    attention head sharding); scalars/cursors replicate.  Leaf shapes:
    [B, kvh, S, hd] unscanned, [L, B, kvh, S, hd] scanned."""
    tensor = mesh.shape.get('tensor', 1)
    if leaf.ndim == 4 and leaf.shape[1] % max(tensor, 1) == 0:
        return NamedSharding(mesh, P(None, 'tensor', None, None))
    if leaf.ndim == 5 and leaf.shape[2] % max(tensor, 1) == 0:
        return NamedSharding(mesh, P(None, None, 'tensor', None, None))
    return NamedSharding(mesh, P())


class InferenceEngine:
    """Batched KV-cache generation over a (possibly sharded) model."""

    def __init__(self, model: str = 'llama-tiny',
                 mesh=None,
                 params: Any = None,
                 checkpoint_dir: Optional[str] = None,
                 max_batch_size: int = 4,
                 max_seq_len: Optional[int] = None,
                 model_overrides: Optional[Dict[str, Any]] = None,
                 param_dtype: Any = jnp.bfloat16,
                 prefill_bucket: int = 64,
                 seed: int = 0) -> None:
        overrides = dict(model_overrides or {})
        overrides.update(decode=True, remat=False)
        overrides.setdefault('param_dtype', param_dtype)
        if max_seq_len is not None:
            overrides['max_seq_len'] = max_seq_len
        self.model, self.config = models_lib.get_model(model, **overrides)
        self.max_batch = max_batch_size
        self.max_seq_len = self.config.max_seq_len
        self.prefill_bucket = max(1, prefill_bucket)
        self.mesh = mesh

        init_tokens = jnp.zeros((max_batch_size, 1), jnp.int32)
        rng = jax.random.PRNGKey(seed)

        def _init():
            return self.model.init(rng, init_tokens)

        abstract = jax.eval_shape(_init)
        if mesh is not None:
            param_shardings = sharding_lib.unbox(
                sharding_lib.params_to_shardings(mesh,
                                                 abstract['params']))
            cache_shardings = jax.tree.map(
                functools.partial(_cache_sharding, mesh),
                abstract['cache'])
        else:
            param_shardings = cache_shardings = None

        self._cache_shardings = cache_shardings
        self._abstract_cache = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            sharding_lib.unbox(abstract['cache']))
        if params is not None:
            self.params = self._place(params, param_shardings)
        elif checkpoint_dir is not None:
            self.params = self._load_checkpoint(checkpoint_dir,
                                                abstract['params'],
                                                param_shardings)
        else:
            logger.warning('InferenceEngine: no params/checkpoint given '
                           '— serving randomly initialized weights '
                           '(tests/dev only).')

            def _init_params():
                return sharding_lib.unbox(_init())['params']
            if mesh is not None:
                self.params = jax.jit(
                    _init_params, out_shardings=param_shardings)()
            else:
                self.params = _init_params()

        def _forward(p, cache, tokens, positions, kv_mask):
            logits, mutated = self.model.apply(
                {'params': p, 'cache': cache}, tokens, positions,
                kv_mask, mutable=['cache'])
            return logits, mutated['cache']

        # Prefill: donate the cache buffers (they are replaced).
        self._prefill = jax.jit(_forward, donate_argnums=(1,))

        def _decode_step(p, cache, last_logits, kv_mask, lengths,
                         prefill_len, step, rng, active,
                         temperature: float, top_k: int, top_p: float):
            """Fused: sample from last logits -> reveal the new slot ->
            one-token forward.  Returns (token, next logits, cache,
            kv_mask).

            The new token's K/V land at the cache *cursor*
            (prefill_len + step — prompts are right-padded to
            prefill_len), while its rope position is the row's true
            length + step; the kv mask bridges the difference.

            Only the fields sampling actually uses are static compile
            keys — max_new_tokens / eos_id live in the host loop and
            must not fragment the compile cache.
            """
            step_rng = jax.random.fold_in(rng, step)
            next_tok = sample_logits(
                last_logits, step_rng,
                SamplingConfig(temperature=temperature, top_k=top_k,
                               top_p=top_p))
            slot = prefill_len + step
            kv_mask = jax.lax.dynamic_update_slice(
                kv_mask, active[:, None], (0, slot))
            positions = (lengths + step)[:, None]
            logits, cache = _forward(p, cache, next_tok[:, None],
                                     positions, kv_mask)
            return next_tok, logits[:, 0], cache, kv_mask

        self._decode = jax.jit(
            _decode_step,
            static_argnames=('temperature', 'top_k', 'top_p'),
            donate_argnums=(1, 3))
        self._rng = jax.random.PRNGKey(seed + 1)
        self._generation = 0

    # -- weights -----------------------------------------------------------
    def _place(self, params, shardings):
        cast = jax.tree.map(
            lambda x: jnp.asarray(x, self.config.param_dtype)
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else
            jnp.asarray(x), params)
        if shardings is None:
            return cast
        return jax.device_put(cast, shardings)

    def _load_checkpoint(self, directory: str, abstract_params,
                         shardings):
        """Load params from a trainer checkpoint (train/checkpoint.py
        layouts, split or legacy) — params only, restored directly into
        the serving shardings."""
        from skypilot_tpu.train import checkpoint as ckpt_lib
        manager = ckpt_lib.make_manager(directory)
        latest = manager.latest_step()
        if latest is None:
            raise FileNotFoundError(
                f'no checkpoint found under {directory!r}')
        abstract = sharding_lib.unbox(abstract_params)
        if shardings is not None:
            abs_tree = jax.tree.map(
                lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                                  sharding=s),
                abstract, shardings)
        else:
            abs_tree = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                abstract)
        try:
            restored = ckpt_lib.load_params_for_serving(
                manager, abs_tree, step=latest)
        except ValueError as e:
            # Genuine tree/shape mismatch; other failures (network,
            # auth, corruption) propagate with their own tracebacks.
            hint = ''
            if any('pos_embed' in '/'.join(map(str, path))
                   for path, _ in jax.tree_util.tree_flatten_with_path(
                       abs_tree)[0]):
                hint = (' (this family sizes pos_embed by max_seq_len; '
                        'serve with the same max_seq_len the model was '
                        'trained with)')
            raise ValueError(
                f'checkpoint param tree does not match model '
                f'{self.config.name!r}: {e}{hint}') from e
        logger.info(f'loaded checkpoint step {latest} from {directory}')
        return restored

    def _fresh_cache(self):
        def _make(leaf, sharding=None):
            if sharding is not None:
                return jnp.zeros(leaf.shape, leaf.dtype,
                                 device=sharding)
            return jnp.zeros(leaf.shape, leaf.dtype)
        if self._cache_shardings is None:
            return jax.tree.map(_make, self._abstract_cache)
        return jax.tree.map(_make, self._abstract_cache,
                            self._cache_shardings)

    def _bucketed(self, s_max: int) -> int:
        b = self.prefill_bucket
        padded = ((s_max + b - 1) // b) * b
        return min(padded, self.max_seq_len)

    # -- generation --------------------------------------------------------
    def generate(self, prompts: Sequence[Sequence[int]],
                 sampling: Optional[SamplingConfig] = None
                 ) -> List[List[int]]:
        """Generate continuations for up to `max_batch_size` prompts of
        (possibly) different lengths. Returns one id list per prompt."""
        cfg = sampling or SamplingConfig()
        n = len(prompts)
        if n == 0:
            return []
        if n > self.max_batch:
            raise ValueError(
                f'{n} prompts > max_batch_size={self.max_batch}.')
        lengths = np.array([len(p) for p in prompts], np.int32)
        if (lengths <= 0).any():
            raise ValueError('empty prompt')
        if int(lengths.max()) + cfg.max_new_tokens > self.max_seq_len:
            raise ValueError(
                f'prompt ({int(lengths.max())}) + max_new_tokens '
                f'({cfg.max_new_tokens}) exceeds max_seq_len '
                f'{self.max_seq_len}.')
        # Bucket the padded prompt length so prefill compiles once per
        # bucket, not once per (prompt length, max_new_tokens) pair;
        # only near the max_seq_len ceiling does the clamp reintroduce
        # a max_new dependence.
        lmax = int(lengths.max())
        s_max = min(self._bucketed(lmax),
                    self.max_seq_len - cfg.max_new_tokens)
        s_max = max(s_max, lmax)

        b = self.max_batch
        tokens = np.zeros((b, s_max), np.int32)
        prompt_mask = np.zeros((b, s_max), bool)
        for i, p in enumerate(prompts):
            tokens[i, :len(p)] = p
            prompt_mask[i, :len(p)] = True
        full_lengths = np.zeros((b,), np.int32)
        full_lengths[:n] = lengths

        kv_mask = jnp.zeros((b, self.max_seq_len), bool)
        kv_mask = kv_mask.at[:, :s_max].set(jnp.asarray(prompt_mask))
        positions = jnp.broadcast_to(
            jnp.arange(s_max, dtype=jnp.int32)[None], (b, s_max))
        lengths_dev = jnp.asarray(full_lengths)

        cache = self._fresh_cache()
        self._generation += 1
        rng = jax.random.fold_in(self._rng, self._generation)
        ctx = self.mesh if self.mesh is not None \
            else contextlib.nullcontext()
        with ctx:
            logits, cache = self._prefill(
                self.params, cache, jnp.asarray(tokens), positions,
                kv_mask)
            last = logits[jnp.arange(b),
                          jnp.maximum(lengths_dev - 1, 0)]

            outputs: List[List[int]] = [[] for _ in range(n)]
            done = np.zeros((b,), bool)
            done[n:] = True
            for t in range(cfg.max_new_tokens):
                tok_dev, last, cache, kv_mask = self._decode(
                    self.params, cache, last, kv_mask, lengths_dev,
                    jnp.int32(s_max), jnp.int32(t), rng,
                    jnp.asarray(~done), temperature=cfg.temperature,
                    top_k=cfg.top_k, top_p=cfg.top_p)
                next_tok = np.asarray(jax.device_get(tok_dev))
                for i in range(n):
                    if not done[i]:
                        outputs[i].append(int(next_tok[i]))
                        if cfg.eos_id is not None and \
                                int(next_tok[i]) == cfg.eos_id:
                            done[i] = True
                if done.all():
                    break
        return outputs
